"""Pipeline ledger (obs/ledger.py) tests: stage-graph completeness, the
Little's-law math on a synthetic ledger, staleness percentiles against
numpy, ring/table overflow behavior, concurrent stamping, the
InflightWindow discard accounting (ISSUE 8 satellite), MFU math, the
aggregator's ledger folds, the report CLI — and a tier-1 driver smoke
asserting a real traced run emits a complete ledger with zero open
records at clean exit whose report names the dominant stage."""

import glob
import json
import os
import threading

import numpy as np
import pytest

from scalable_agent_tpu.obs.ledger import (
    SEGMENT_LABELS,
    SEGMENTS,
    SERVICE_STAGES,
    STAGES,
    TIMING_STAGE_MAP,
    PipelineLedger,
    peak_flops_per_chip,
)
from scalable_agent_tpu.obs.registry import MetricsRegistry


def _ledger(**kwargs):
    kwargs.setdefault("registry", MetricsRegistry())
    kwargs.setdefault("frames_per_trajectory", 100.0)
    return PipelineLedger(**kwargs)


def _walk(ledger, birth_us, stamps, retired=True, actor="a0",
          group="g"):
    """One record with explicit stage timestamps (us)."""
    tid = ledger.open(actor, group, birth_us=birth_us)
    for stage, ts in stamps.items():
        ledger.stamp(tid, stage, ts_us=ts)
    ledger.close(tid, retired=retired)
    return tid


class TestStageGraph:
    def test_segments_chain_birth_to_retire(self):
        """The segments form one unbroken chain over the stage list —
        their durations partition birth→retire exactly."""
        assert SEGMENTS[0][1] == "birth"
        assert SEGMENTS[-1][2] == "retire"
        for (_, _, end), (_, start, _) in zip(SEGMENTS, SEGMENTS[1:]):
            assert end == start, "segment chain has a gap"
        for _, start, end in SEGMENTS:
            assert start in STAGES and end in STAGES

    def test_stage_order_matches_pipeline(self):
        assert STAGES.index("birth") < STAGES.index("unroll_done")
        assert STAGES.index("queue_put") < STAGES.index("queue_get")
        assert STAGES.index("dispatch") < STAGES.index("retire")

    def test_timing_map_targets_exist(self):
        names = {name for name, _, _ in SEGMENTS} | set(SERVICE_STAGES)
        for metric, segment in TIMING_STAGE_MAP.items():
            assert segment in names, (metric, segment)
        for name in names:
            assert name in SEGMENT_LABELS

    def test_full_walk_covers_every_segment(self):
        ledger = _ledger()
        stamps = {stage: (i + 1) * 1_000_000
                  for i, stage in enumerate(STAGES[1:])}
        _walk(ledger, 0, stamps)
        stats = ledger.publish(interval_s=10.0)
        for name, _, _ in SEGMENTS:
            assert stats["segments"][name]["count"] == 1, name


class TestLittlesLaw:
    def test_rates_rho_and_w_agree(self):
        """L = λ·W: the published ρ (busy seconds per wall second) must
        equal rate x mean latency for every segment — the decomposition
        the report's 'which stage holds the frames' column rests on."""
        ledger = _ledger()
        interval = 20.0
        n = 8
        queue_wait_s = 3.0
        for k in range(n):
            base = k * 1_000_000
            _walk(ledger, base, {
                "unroll_done": base + 500_000,
                "queue_put": base + 600_000,
                "queue_get": base + 600_000
                + int(queue_wait_s * 1e6),
                "put_done": base + 3_700_000,
                "dispatch": base + 3_800_000,
                "retire": base + 4_000_000,
            })
        stats = ledger.publish(interval_s=interval)
        seg = stats["segments"]["queue_wait"]
        lam = n / interval
        assert seg["rate_per_s"] == pytest.approx(lam)
        assert seg["mean_s"] == pytest.approx(queue_wait_s)
        # Little's law: L (the published rho) = λ · W.
        assert seg["rho"] == pytest.approx(lam * queue_wait_s)
        # And the unroll segment independently:
        seg = stats["segments"]["unroll"]
        assert seg["rho"] == pytest.approx(lam * 0.5)

    def test_latency_shares_partition_birth_to_retire(self):
        ledger = _ledger()
        _walk(ledger, 0, {
            "unroll_done": 1_000_000, "queue_put": 1_000_000,
            "queue_get": 8_000_000, "put_done": 9_000_000,
            "dispatch": 9_000_000, "retire": 10_000_000})
        ledger.publish(interval_s=5.0)
        shares = ledger.latency_shares()
        assert sum(shares.values()) == pytest.approx(1.0)
        assert shares["queue_wait"] == pytest.approx(0.7)
        assert ledger.dominant_segment() == (
            "queue_wait", pytest.approx(0.7))

    def test_shares_persist_across_empty_intervals(self):
        """A log interval with no closed records must keep the last
        attribution, not blank the verdict line."""
        ledger = _ledger()
        _walk(ledger, 0, {"unroll_done": 1_000_000,
                          "retire": 2_000_000})
        ledger.publish(interval_s=1.0)
        before = ledger.latency_shares()
        assert before
        ledger.publish(interval_s=1.0)  # nothing closed since
        assert ledger.latency_shares() == before

    def test_negative_skew_clamps_to_zero(self):
        """queue_put/queue_get race across threads by design; a few us
        of skew must clamp, not go negative."""
        ledger = _ledger()
        _walk(ledger, 0, {"queue_put": 2_000_000,
                          "queue_get": 1_999_000,
                          "retire": 3_000_000})
        stats = ledger.publish(interval_s=1.0)
        assert stats["segments"]["queue_wait"]["mean_s"] == 0.0


class TestStaleness:
    def test_percentiles_match_numpy(self):
        ledger = _ledger()
        registry = ledger._registry
        ages_s = np.linspace(0.5, 12.0, 101)
        for i, age in enumerate(ages_s):
            base = i * 20_000_000
            _walk(ledger, base, {"retire": base + int(age * 1e6)})
        snap = registry.snapshot()
        for q in (50, 95, 99):
            expected = float(np.percentile(ages_s, q))
            assert snap[f"ledger/staleness_s/p{q}"] == pytest.approx(
                expected, rel=1e-6), q
        assert snap["ledger/staleness_s/count"] == len(ages_s)

    def test_only_retired_records_feed_staleness(self):
        ledger = _ledger()
        registry = ledger._registry
        _walk(ledger, 0, {"retire": 1_000_000}, retired=True)
        _walk(ledger, 0, {}, retired=False)
        assert registry.snapshot()["ledger/staleness_s/count"] == 1


class TestOverflow:
    def test_open_table_overflow_drops_oldest_and_flags(self):
        ledger = _ledger(open_capacity=4)
        tids = [ledger.open("a", "g") for _ in range(6)]
        registry = ledger._registry
        snap = registry.snapshot()
        assert snap["ledger/records_dropped_total"] == 2.0
        assert snap["ledger/truncated"] == 1.0
        assert snap["ledger/open_records"] == 4.0
        # The evicted records' late stamps are counted, not crashed on.
        ledger.stamp(tids[0], "dispatch")
        assert registry.snapshot()["ledger/late_stamps_total"] == 1.0

    def test_closed_window_overflow_counts_dropped(self):
        ledger = _ledger(closed_capacity=3)
        for _ in range(5):
            tid = ledger.open("a", "g")
            ledger.close(tid, retired=True)
        assert ledger._registry.snapshot()[
            "ledger/records_dropped_total"] == 2.0

    def test_ring_truncation_marker_in_snapshot(self):
        ledger = _ledger(ring_capacity=8)
        assert ledger.snapshot()["ring_truncated"] is False
        for _ in range(5):
            tid = ledger.open("a", "g")
            ledger.close(tid, retired=True)
        snap = ledger.snapshot()
        assert snap["ring_truncated"] is True
        assert len(snap["ring_tail"]) <= 8


class TestConcurrency:
    def test_concurrent_stamping_exact_counts(self):
        """8 threads x 50 full record lifecycles: every record closes,
        counts are exact, nothing leaks open."""
        ledger = _ledger(open_capacity=4096, closed_capacity=4096)
        per_thread = 50
        threads = 8
        errors = []

        def worker():
            try:
                for _ in range(per_thread):
                    tid = ledger.open("t", "g")
                    for stage in ("unroll_done", "queue_put",
                                  "queue_get", "put_done", "dispatch"):
                        ledger.stamp(tid, stage)
                    ledger.close(tid, retired=True)
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        pool = [threading.Thread(target=worker) for _ in range(threads)]
        for t in pool:
            t.start()
        for t in pool:
            t.join()
        assert not errors
        snap = ledger._registry.snapshot()
        total = threads * per_thread
        assert snap["ledger/trajectories_opened_total"] == total
        assert snap["ledger/trajectories_retired_total"] == total
        assert snap["ledger/open_records"] == 0.0
        stats = ledger.publish(interval_s=1.0)
        assert stats["records"] == total

    def test_current_is_thread_local(self):
        ledger = _ledger()
        ledger.set_current(7)
        seen = []

        def other():
            seen.append(ledger.current())
            ledger.set_current(9)

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert seen == [None]
        assert ledger.current() == 7


class TestBindings:
    def test_bind_lookup_is_one_shot(self):
        ledger = _ledger()
        ledger.bind(111, 5)
        assert ledger.lookup(111) == 5
        assert ledger.lookup(111) is None

    def test_unbind_clears(self):
        ledger = _ledger()
        ledger.bind(111, 5)
        assert ledger.unbind(111) == 5
        assert ledger.lookup(111) is None

    def test_binding_table_is_bounded(self):
        ledger = _ledger(bind_capacity=4)
        for key in range(8):
            ledger.bind(key, key)
        assert len(ledger._bindings) <= 4
        assert ledger.lookup(7) == 7  # newest survive


class TestDiscardAccounting:
    """ISSUE 8 satellite: InflightWindow.discard must record its
    records as retired=False with frames in frames_discarded_total —
    today's rollback path may not leak open records."""

    def test_inflight_discard_closes_retired_false(self):
        from scalable_agent_tpu.runtime.transport import InflightWindow

        from scalable_agent_tpu.obs import ledger as ledger_mod

        registry = MetricsRegistry()
        ledger = ledger_mod.configure_ledger(
            registry=registry, frames_per_trajectory=128.0)
        try:
            window = InflightWindow(4, registry=registry)
            tids = []
            for k in range(3):
                tid = ledger.open("a", "g")
                ledger.stamp(tid, "dispatch")
                window.push({"total_loss": float(k)}, ledger_id=tid)
                tids.append(tid)
            assert window.discard() == 3
            snap = registry.snapshot()
            assert snap["ledger/trajectories_discarded_total"] == 3.0
            assert snap["ledger/frames_discarded_total"] == 3 * 128.0
            assert snap["ledger/trajectories_retired_total"] == 0.0
            assert snap["ledger/open_records"] == 0.0
        finally:
            ledger_mod.configure_ledger()

    def test_inflight_retire_closes_retired_true(self):
        from scalable_agent_tpu.runtime.transport import InflightWindow

        from scalable_agent_tpu.obs import ledger as ledger_mod

        registry = MetricsRegistry()
        ledger = ledger_mod.configure_ledger(
            registry=registry, frames_per_trajectory=128.0)
        try:
            window = InflightWindow(2, registry=registry)
            tid = ledger.open("a", "g")
            ledger.stamp(tid, "dispatch")
            window.push({"x": 1.0}, ledger_id=tid)
            assert window.retire() == {"x": 1.0}
            snap = registry.snapshot()
            assert snap["ledger/trajectories_retired_total"] == 1.0
            assert snap["ledger/open_records"] == 0.0
            assert snap["ledger/staleness_s/count"] == 1.0
        finally:
            ledger_mod.configure_ledger()

    def test_finalize_sweeps_open_records_as_abandoned(self, tmp_path):
        ledger = _ledger(logdir=str(tmp_path), frames_per_trajectory=64)
        ledger.open("a", "g")
        ledger.open("a", "g")
        path = ledger.finalize()
        snap = ledger._registry.snapshot()
        assert snap["ledger/open_records"] == 0.0
        assert snap["ledger/trajectories_abandoned_total"] == 2.0
        assert snap["ledger/frames_discarded_total"] == 128.0
        artifact = json.load(open(path))
        assert artifact["counters"]["abandoned"] == 2.0
        assert artifact["open_records"] == []


class TestMfuAndServices:
    def test_mfu_math(self):
        ledger = _ledger()
        ledger.configure_mfu(flops_per_update=1e9, peak_flops=1e12,
                             num_devices=2)
        for _ in range(4):
            tid = ledger.open("a", "g")
            ledger.close(tid, retired=True)
        stats = ledger.publish(interval_s=2.0)
        # 4 updates in 2s x 1e9 flops / (1e12 x 2 devices) = 1e-3.
        assert stats["mfu"] == pytest.approx(1e-3)
        assert ledger._registry.snapshot()[
            "ledger/mfu"] == pytest.approx(1e-3)

    def test_peak_flops_table(self):
        assert peak_flops_per_chip("TPU v5 lite") == 197e12
        assert peak_flops_per_chip("TPU v5p fancy") == 459e12
        assert peak_flops_per_chip("cpu") is None
        # bench.py must resolve through the SAME table.
        import bench

        assert bench._peak_flops("TPU v4 pod") == 275e12

    def test_note_service_rho(self):
        ledger = _ledger()
        ledger.note_service("inference_service", 8, 0.5)
        ledger.note_service("inference_service", 8, 0.3)
        stats = ledger.publish(interval_s=4.0)
        seg = stats["segments"]["inference_service"]
        assert seg["rate_per_s"] == pytest.approx(4.0)
        assert seg["rho"] == pytest.approx(0.2)

    def test_batcher_feeds_service_stage(self):
        from scalable_agent_tpu.obs import ledger as ledger_mod
        from scalable_agent_tpu.runtime.batcher import DynamicBatcher

        registry = MetricsRegistry()
        ledger = ledger_mod.configure_ledger(registry=registry)
        try:
            with DynamicBatcher(lambda tree, n: tree,
                                minimum_batch_size=1,
                                maximum_batch_size=4,
                                timeout_ms=5.0,
                                registry=registry) as batcher:
                assert batcher.compute(np.float32(3.0)) == 3.0
            stats = ledger.publish(interval_s=1.0)
            assert stats["segments"]["inference_service"][
                "rate_per_s"] >= 1.0
        finally:
            ledger_mod.configure_ledger()


class TestAggregatorFolds:
    """ISSUE 8 satellite: ledger/* folds fleet-wide — rates sum, ρ and
    shares max, staleness quantiles max (metrics.fleet.prom)."""

    def _proms(self):
        def render(rate, rho, stale_p99, frames):
            return "\n".join([
                "# TYPE impala_ledger_rate_transport_per_s gauge",
                f"impala_ledger_rate_transport_per_s {rate}",
                "# TYPE impala_ledger_rho_transport gauge",
                f"impala_ledger_rho_transport {rho}",
                "# TYPE impala_ledger_latency_share_transport gauge",
                f"impala_ledger_latency_share_transport {rho}",
                "# TYPE impala_ledger_staleness_s summary",
                f'impala_ledger_staleness_s{{quantile="0.99"}} '
                f"{stale_p99}",
                "# TYPE impala_ledger_frames_discarded_total counter",
                f"impala_ledger_frames_discarded_total {frames}",
                "# TYPE impala_ledger_mfu gauge",
                f"impala_ledger_mfu {rho}",
            ]) + "\n"

        return {"0": render(2.0, 0.25, 1.5, 100.0),
                "1": render(3.0, 0.75, 4.5, 50.0)}

    def test_ledger_fold_rules(self):
        from scalable_agent_tpu.obs.aggregate import (
            aggregate_prometheus,
            parse_prometheus,
        )

        folded = parse_prometheus(aggregate_prometheus(self._proms()))

        def fleet(family, metric=None, quantile=None):
            metric = metric or family
            for (name, labels), value in folded[family]["series"].items():
                ldict = dict(labels)
                if name == metric and "fold" in ldict and (
                        quantile is None
                        or ldict.get("quantile") == quantile):
                    return ldict["fold"], value
            raise KeyError((family, metric))

        assert fleet("impala_ledger_rate_transport_per_s") == (
            "sum", 5.0)
        assert fleet("impala_ledger_rho_transport") == ("max", 0.75)
        assert fleet("impala_ledger_latency_share_transport") == (
            "max", 0.75)
        assert fleet("impala_ledger_mfu") == ("max", 0.75)
        assert fleet("impala_ledger_staleness_s",
                     quantile="0.99") == ("max", 4.5)
        assert fleet("impala_ledger_frames_discarded_total") == (
            "sum", 150.0)


class TestStallIntegration:
    def test_verdict_carries_dominant_stage(self):
        from scalable_agent_tpu.obs import StallAttributor
        from scalable_agent_tpu.obs import ledger as ledger_mod

        registry = MetricsRegistry()
        ledger = ledger_mod.configure_ledger(registry=registry)
        try:
            _walk(ledger, 0, {
                "unroll_done": 1_000_000, "queue_put": 1_000_000,
                "queue_get": 8_800_000, "put_done": 9_000_000,
                "dispatch": 9_000_000, "retire": 10_000_000})
            ledger.publish(interval_s=5.0)
            attributor = StallAttributor(registry)
            registry.histogram("actor/inference_s").observe(3.0)
            category, evidence = attributor.attribute(0.8, 0.2)
            assert category == "learner_starved"
            assert evidence["ledger_dominant"] == "queue_wait"
            line = StallAttributor.describe(category, evidence)
            assert "of frame latency in batcher wait" in line
            assert "78%" in line
        finally:
            ledger_mod.configure_ledger()

    def test_verdict_clean_without_ledger_data(self):
        from scalable_agent_tpu.obs import StallAttributor
        from scalable_agent_tpu.obs import ledger as ledger_mod

        registry = MetricsRegistry()
        ledger_mod.configure_ledger(registry=registry)
        try:
            attributor = StallAttributor(registry)
            category, evidence = attributor.attribute(0.0, 1.0)
            assert "ledger_dominant" not in evidence
            line = StallAttributor.describe(category, evidence)
            assert "frame latency" not in line
        finally:
            ledger_mod.configure_ledger()


class TestReportCli:
    def _write_prom(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        lines = []
        rows = {
            "unroll": (4.0, 0.4, 0.1),
            "backpressure": (4.0, 0.1, 0.02),
            "queue_wait": (4.0, 8.0, 0.70),
            "transport": (4.0, 0.2, 0.05),
            "staged_wait": (4.0, 0.3, 0.08),
            "device": (4.0, 0.2, 0.05),
        }
        for name, (rate, rho, share) in rows.items():
            lines += [
                f"# TYPE impala_ledger_rate_{name}_per_s gauge",
                f"impala_ledger_rate_{name}_per_s {rate}",
                f"# TYPE impala_ledger_rho_{name} gauge",
                f"impala_ledger_rho_{name} {rho}",
                f"# TYPE impala_ledger_latency_share_{name} gauge",
                f"impala_ledger_latency_share_{name} {share}",
                f"# TYPE impala_ledger_stage_{name}_s summary",
                f'impala_ledger_stage_{name}_s{{quantile="0.95"}} '
                f"{rho / rate}",
                f"impala_ledger_stage_{name}_s_sum {rho * 10.0}",
                f"impala_ledger_stage_{name}_s_count {rate * 10.0}",
            ]
        lines += [
            "# TYPE impala_ledger_staleness_s summary",
            'impala_ledger_staleness_s{quantile="0.5"} 0.8',
            'impala_ledger_staleness_s{quantile="0.95"} 1.2',
            'impala_ledger_staleness_s{quantile="0.99"} 1.4',
            "# TYPE impala_ledger_mfu gauge",
            "impala_ledger_mfu 0.15",
            "# TYPE impala_stall_is_learner_starved gauge",
            "impala_stall_is_learner_starved 1.0",
            "# TYPE impala_ledger_trajectories_opened_total counter",
            "impala_ledger_trajectories_opened_total 40.0",
            "# TYPE impala_ledger_trajectories_retired_total counter",
            "impala_ledger_trajectories_retired_total 40.0",
            "# TYPE impala_ledger_frames_discarded_total counter",
            "impala_ledger_frames_discarded_total 0.0",
            "# TYPE impala_ledger_open_records gauge",
            "impala_ledger_open_records 0.0",
        ]
        with open(os.path.join(logdir, "metrics.prom"), "w") as f:
            f.write("\n".join(lines) + "\n")

    def test_report_names_dominant_stage(self, tmp_path, capsys):
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        self._write_prom(logdir)
        assert report.main([logdir]) == 0
        out = capsys.readouterr().out
        assert "queue_wait" in out
        assert "dominant stage: queue_wait (70% of frame latency" in out
        assert "top recommendation:" in out
        assert "staleness" in out and "p99 1.400s" in out
        assert "mfu: 0.15" in out
        assert "stall verdict: learner_starved" in out

    def test_report_errors_without_artifacts(self, tmp_path, capsys):
        from scalable_agent_tpu.obs import report

        # Operator-error convention shared with obs.watch/obs.diagnose:
        # exit 2, one diagnostic line on stderr.
        assert report.main([str(tmp_path)]) == 2
        assert "no metrics" in capsys.readouterr().err

    def _append_replay_series(self, logdir, replayed_p95):
        with open(os.path.join(logdir, "metrics.prom"), "a") as f:
            f.write("\n".join([
                "# TYPE impala_ledger_staleness_replayed_s summary",
                'impala_ledger_staleness_replayed_s{quantile="0.5"} '
                f"{replayed_p95 * 0.8}",
                'impala_ledger_staleness_replayed_s{quantile="0.95"} '
                f"{replayed_p95}",
                'impala_ledger_staleness_replayed_s{quantile="0.99"} '
                f"{replayed_p95 * 1.1}",
                "# TYPE impala_replay_occupancy gauge",
                "impala_replay_occupancy 0.5",
                "# TYPE impala_replay_insert_total counter",
                "impala_replay_insert_total 40.0",
                "# TYPE impala_replay_sampled_total counter",
                "impala_replay_sampled_total 80.0",
                "# TYPE impala_replay_target_update_interval gauge",
                "impala_replay_target_update_interval 100.0",
            ]) + "\n")

    def test_report_renders_staleness_split_and_replay(self, tmp_path,
                                                      capsys):
        """ISSUE 13 satellite: fresh vs replayed staleness render as
        two series, the slab counters show, and a replayed p95 INSIDE
        the IMPACT clip's useful range draws no recommendation."""
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        self._write_prom(logdir)
        # Useful range = interval 100 / device rate 4.0 = 25s.
        self._append_replay_series(logdir, replayed_p95=5.0)
        assert report.main([logdir]) == 0
        out = capsys.readouterr().out
        assert "staleness (FRESH frame age" in out
        assert "staleness (REPLAYED frame age" in out
        assert "p95 5.000s" in out
        assert "replay slab: occupancy 0.50, 40 inserted, 80 sampled" \
            in out
        assert "replay recommendation:" not in out

    def test_report_recommends_when_replayed_staleness_exceeds_clip(
            self, tmp_path, capsys):
        """The dial's warning light: replayed p95 beyond ~one target
        refresh period (target_update_interval / update rate) means
        the sampled data predates the clip's anchor — the report must
        say so and name the knobs."""
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        self._write_prom(logdir)
        self._append_replay_series(logdir, replayed_p95=60.0)
        assert report.main([logdir]) == 0
        out = capsys.readouterr().out
        assert "replay recommendation:" in out
        assert "exceeds the IMPACT clip's useful range" in out
        assert "--replay_ratio" in out

    def test_report_without_replay_is_unchanged(self, tmp_path, capsys):
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        self._write_prom(logdir)
        assert report.main([logdir]) == 0
        out = capsys.readouterr().out
        assert "REPLAYED" not in out
        assert "replay slab:" not in out

    def test_impact_without_replay_draws_no_slab_section(
            self, tmp_path, capsys):
        """--loss=impact publishes the anchor-cadence gauge even with
        replay off — the report must not render a phantom slab."""
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        self._write_prom(logdir)
        with open(os.path.join(logdir, "metrics.prom"), "a") as f:
            f.write(
                "# TYPE impala_replay_target_update_interval gauge\n"
                "impala_replay_target_update_interval 100.0\n")
        assert report.main([logdir]) == 0
        out = capsys.readouterr().out
        assert "replay slab:" not in out
        assert "replay recommendation:" not in out


# ---------------------------------------------------------------------------
# Tier-1 driver smoke (ISSUE 8 acceptance): a single-chip traced run
# emits the staleness histogram, per-stage utilization gauges, a live
# MFU gauge, a complete ledger with zero open records at clean exit —
# and the report CLI's dominant-stage attribution agrees with the
# published shares.
# ---------------------------------------------------------------------------


def test_traced_driver_run_emits_complete_ledger(tmp_path, monkeypatch,
                                                 capsys):
    from scalable_agent_tpu.config import Config
    from scalable_agent_tpu.driver import train as run_train
    from scalable_agent_tpu.obs import report

    # Force the MFU path on CPU: a synthetic peak makes the gauge
    # nonzero without a TPU roofline entry.
    monkeypatch.setenv("SCALABLE_AGENT_LEDGER_MFU_PEAK", "1e12")
    config = Config(
        mode="train",
        logdir=str(tmp_path / "run"),
        level_name="fake_small",
        num_actors=4,
        batch_size=2,
        unroll_length=4,
        num_action_repeats=1,
        total_environment_frames=32,  # 4 updates of 8 frames
        height=16,
        width=16,
        num_env_workers_per_group=2,
        compute_dtype="float32",
        checkpoint_interval_s=1e9,
        log_interval_s=0.0,
        trace=True,
        seed=5,
    )
    # The ledger counters live on the PROCESS-GLOBAL registry and
    # accumulate across every driver run in this pytest session —
    # conservation must be asserted on THIS run's deltas.
    from scalable_agent_tpu.obs import get_registry

    def _counters():
        snap = get_registry().snapshot()
        return {key: snap.get(f"ledger/trajectories_{key}_total", 0.0)
                for key in ("opened", "retired", "discarded",
                            "abandoned")}

    before = _counters()
    metrics = run_train(config)
    assert metrics["env_frames"] == 32
    delta = {key: value - before[key]
             for key, value in _counters().items()}

    # -- the ledger artifact: complete, zero open records -----------------
    paths = glob.glob(os.path.join(config.logdir, "ledger.p0.json"))
    assert len(paths) == 1, paths
    artifact = json.load(open(paths[0]))
    assert artifact["open_records"] == []
    assert delta["retired"] >= 4  # one per update
    # Conservation: every record THIS run opened was closed one way.
    assert delta["opened"] == (delta["retired"] + delta["discarded"]
                               + delta["abandoned"])
    # The stamp ring saw real stage crossings in pipeline order.
    stages_seen = {e["stage"] for e in artifact["ring_tail"]}
    for stage in ("birth", "unroll_done", "queue_put", "queue_get",
                  "put_done", "dispatch", "retire"):
        assert stage in stages_seen, stage

    # -- the prometheus snapshot ------------------------------------------
    text = open(os.path.join(config.logdir, "metrics.prom")).read()
    assert 'impala_ledger_staleness_s{quantile="0.5"}' in text
    assert 'impala_ledger_staleness_s{quantile="0.99"}' in text
    values = {}
    for line in text.splitlines():
        if line.startswith("impala_ledger") and " " in line \
                and not line.startswith("#"):
            key, _, value = line.rpartition(" ")
            try:
                values[key] = float(value)
            except ValueError:
                pass
    assert values["impala_ledger_open_records"] == 0.0
    assert values["impala_ledger_mfu"] > 0.0  # the live MFU gauge
    shares = {name: values[f"impala_ledger_latency_share_{name}"]
              for name, _, _ in SEGMENTS}
    assert sum(shares.values()) == pytest.approx(1.0, abs=1e-6)
    for name, _, _ in SEGMENTS:
        assert f"impala_ledger_rho_{name}" in values, name

    # -- the report CLI: stage table + dominant-stage attribution ---------
    assert report.main([config.logdir]) == 0
    out = capsys.readouterr().out
    for name, _, _ in SEGMENTS:
        assert name in out, name
    expected_dominant = max(shares, key=shares.get)
    assert (f"dominant stage: {expected_dominant} "
            f"({shares[expected_dominant]:.0%} of frame latency") in out
    assert "top recommendation:" in out
    assert "staleness (FRESH frame age at consumption):" in out
