"""ISSUE 4 satellite: end-to-end preemption resume.

A REAL driver subprocess is SIGKILL'd mid-training — no handler, no
graceful unwind, possibly mid-checkpoint-write — and restarted on the
same logdir with ``--inflight_updates=2``.  The restart must restore a
verified checkpoint (walking past any step the kill tore), continue the
frame-exact LR schedule, and finish with NO frame double-count: the
final checkpoint's on-device ``env_frames`` equals updates x
frames-per-update exactly.  (Extends tests/test_obs_sigterm.py's
subprocess machinery; SIGKILL instead of SIGTERM is the point — nothing
gets to flush.)
"""

import glob
import json
import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.chaos, pytest.mark.slow]

FPU = 2 * 4 * 1  # batch * unroll * action_repeats
LR = 0.00048


def _driver_cmd(logdir, frames):
    return [
        sys.executable, "-m", "scalable_agent_tpu.driver",
        "--mode=train", "--level_name=fake_small", "--logdir", logdir,
        "--num_actors=4", "--batch_size=2", "--unroll_length=4",
        "--num_action_repeats=1",
        f"--total_environment_frames={frames}",
        "--height=16", "--width=16", "--num_env_workers_per_group=2",
        "--compute_dtype=float32", "--checkpoint_interval_s=0.0",
        "--log_interval_s=0.0", "--inflight_updates=2", "--seed=3",
    ]


def _retained_steps(logdir):
    steps = []
    ckpt_dir = os.path.join(logdir, "checkpoints")
    for name in glob.glob(os.path.join(ckpt_dir, "*")):
        base = os.path.basename(name)
        if base.isdigit():
            steps.append(int(base))
    return sorted(steps)


def test_sigkill_mid_training_resumes_frame_exact(tmp_path):
    logdir = str(tmp_path / "run")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    # -- run 1: train toward an unreachable target, SIGKILL once at
    # least two checkpoints are durable (so the walk-back has somewhere
    # to land even if the kill tears the newest step).
    proc = subprocess.Popen(
        _driver_cmd(logdir, 1_000_000), env=env, cwd=cwd,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                pytest.fail("driver exited early:\n"
                            + proc.stdout.read()[-3000:])
            if len(_retained_steps(logdir)) >= 2:
                break
            time.sleep(0.25)
        else:
            pytest.fail("driver produced <2 checkpoints in time")
        proc.kill()  # SIGKILL: no handler, no flush, no final save
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert proc.returncode == -9

    steps_after_kill = _retained_steps(logdir)
    assert steps_after_kill, "no checkpoints survived the kill"
    latest = max(steps_after_kill)

    # Rotate the metrics file so run 2's rows are cleanly separable
    # (MetricsWriter appends).
    jsonl = os.path.join(logdir, "metrics.jsonl")
    if os.path.exists(jsonl):
        os.rename(jsonl, os.path.join(logdir, "metrics.run1.jsonl"))

    # -- run 2: same logdir, reachable target a few updates past the
    # newest retained step.
    target_updates = latest + 3
    target_frames = target_updates * FPU
    out = subprocess.run(
        _driver_cmd(logdir, target_frames), env=env, cwd=cwd,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=420)
    assert out.returncode == 0, out.stdout[-3000:]

    # It resumed from a retained checkpoint (never from scratch), and
    # never from beyond the kill point.
    match = re.search(r"restored checkpoint at update (\d+)",
                      out.stdout)
    assert match, "run 2 did not restore a checkpoint:\n" + \
        out.stdout[-2000:]
    restored_step = int(match.group(1))
    assert 1 <= restored_step <= latest

    # -- continuity: run 2's metrics rows carry frame-exact accounting
    # and an LR keyed on the RESTORED frame count — a resume that had
    # silently restarted env_frames at zero would fail both checks.
    run2 = [json.loads(line) for line in open(jsonl)]
    run2 = [r for r in run2 if "env_frames" in r]
    assert run2, "no metrics rows from the resumed run"
    # First row continues right after the restored step — never from
    # scratch, never skipping ahead.
    assert (restored_step + 1) * FPU <= run2[0]["env_frames"] \
        <= (restored_step + 2) * FPU
    prev = None
    for row in run2:
        frames = row["env_frames"]
        assert frames % FPU == 0, "frame count not a whole update"
        if prev is not None:
            # Non-decreasing, not strictly: an update can be logged
            # twice — once as the newest dispatched fallback, once when
            # it retires from the in-flight window.
            assert frames >= prev, "frame accounting went backwards"
        prev = frames
        # LR decays linearly in the frames BEFORE the update (the
        # reference's frame-keyed polynomial_decay), computed from the
        # restored on-device counter — resume-exact under run 2's
        # schedule denominator.
        expected_lr = LR * max(0.0, 1.0 - (frames - FPU)
                               / target_frames)
        np.testing.assert_allclose(row["learning_rate"], expected_lr,
                                   rtol=1e-4, atol=1e-12)
    # Every update between resume and the kill-free finish is
    # accounted exactly once: the distinct frame counts form a
    # contiguous run of whole updates up to the target.
    distinct = sorted({r["env_frames"] for r in run2})
    assert distinct == [float(f) for f in
                        range(int(distinct[0]), int(distinct[-1]) + FPU,
                              FPU)]
    assert distinct[-1] <= target_frames

    # -- no frame double-count under --inflight_updates=2: the final
    # forced checkpoint's on-device counter is exactly updates x FPU.
    import jax

    jax.config.update("jax_platforms", "cpu")
    from scalable_agent_tpu.runtime.checkpoint import CheckpointManager

    ckpt = CheckpointManager(logdir)
    try:
        step, restored = ckpt.restore()
        assert step == target_updates
        restored_frames = float(np.asarray(restored["env_frames"]))
        assert restored_frames == target_frames
    finally:
        ckpt.close()
