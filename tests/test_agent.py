"""Agent model tests.

Mirrors what the reference relies on but never unit-tests (its Agent has no
test file): unroll shapes, step/unroll equivalence, done-triggered state
reset, and the instruction encoder's length masking.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalable_agent_tpu.models import ImpalaAgent, actor_step, initial_state
from scalable_agent_tpu.models.instruction import (
    InstructionEncoder,
    hash_instruction,
)
from scalable_agent_tpu.types import Observation, StepOutput, StepOutputInfo

NUM_ACTIONS = 5
FRAME = (16, 16, 3)


def make_env_outputs(rng, unroll_len, batch, done=None, instruction=False):
    frame = rng.integers(0, 256, (unroll_len, batch) + FRAME, dtype=np.uint8)
    if done is None:
        done = np.zeros((unroll_len, batch), bool)
    instr = (
        rng.integers(0, 10, (unroll_len, batch, 4), dtype=np.int32)
        if instruction else None)
    return StepOutput(
        reward=rng.standard_normal((unroll_len, batch)).astype(np.float32),
        info=StepOutputInfo(
            episode_return=np.zeros((unroll_len, batch), np.float32),
            episode_step=np.zeros((unroll_len, batch), np.int32)),
        done=done,
        observation=Observation(frame=frame, instruction=instr),
    )


def init_agent(**kwargs):
    agent = ImpalaAgent(num_actions=NUM_ACTIONS, **kwargs)
    rng = np.random.default_rng(0)
    env_outputs = make_env_outputs(
        rng, 1, 1, instruction=kwargs.get("use_instruction", False))
    actions = np.zeros((1, 1), np.int32)
    params = agent.init(
        jax.random.key(0), actions, env_outputs, initial_state(1))
    return agent, params


class TestUnroll:
    def test_shapes(self):
        agent, params = init_agent()
        rng = np.random.default_rng(1)
        unroll_len, batch = 7, 3
        env_outputs = make_env_outputs(rng, unroll_len, batch)
        actions = rng.integers(0, NUM_ACTIONS, (unroll_len, batch)).astype(
            np.int32)
        (logits, baseline), state = agent.apply(
            params, actions, env_outputs, initial_state(batch))
        assert logits.shape == (unroll_len, batch, NUM_ACTIONS)
        assert baseline.shape == (unroll_len, batch)
        assert state.c.shape == (batch, 256)
        assert state.h.shape == (batch, 256)

    def test_unroll_equals_stepwise(self):
        """T-step unroll == T sequential 1-step unrolls (shared weights),

        the property the reference gets from sharing Agent.unroll between
        actor and learner (reference: experiment.py:212-237)."""
        agent, params = init_agent()
        rng = np.random.default_rng(2)
        unroll_len, batch = 5, 2
        done = rng.random((unroll_len, batch)) < 0.3
        env_outputs = make_env_outputs(rng, unroll_len, batch, done=done)
        actions = rng.integers(0, NUM_ACTIONS, (unroll_len, batch)).astype(
            np.int32)

        (full_logits, full_baseline), full_state = agent.apply(
            params, actions, env_outputs, initial_state(batch))

        state = initial_state(batch)
        for t in range(unroll_len):
            step_outputs = jax.tree_util.tree_map(
                lambda x: x[t:t + 1] if x is not None else None,
                env_outputs, is_leaf=lambda x: x is None)
            (logits, baseline), state = agent.apply(
                params, actions[t:t + 1], step_outputs, state)
            np.testing.assert_allclose(
                logits[0], full_logits[t], rtol=2e-5, atol=2e-5)
            np.testing.assert_allclose(
                baseline[0], full_baseline[t], rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(state.c, full_state.c, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(state.h, full_state.h, rtol=2e-5, atol=2e-5)

    def test_done_resets_state(self):
        """A done at step t erases all dependence on pre-t history

        (reference: experiment.py:230-234)."""
        agent, params = init_agent()
        rng = np.random.default_rng(3)
        unroll_len, batch = 4, 1
        done = np.zeros((unroll_len, batch), bool)
        done[2] = True  # episode boundary before step 2's core update
        env_outputs = make_env_outputs(rng, unroll_len, batch, done=done)
        actions = rng.integers(0, NUM_ACTIONS, (unroll_len, batch)).astype(
            np.int32)

        # Same trajectory but with a *different random* pre-boundary history.
        alt = make_env_outputs(rng, unroll_len, batch, done=done)
        alt_frames = np.array(alt.observation.frame)
        alt_frames[2:] = np.asarray(env_outputs.observation.frame)[2:]
        alt_rewards = np.array(alt.reward)
        alt_rewards[2:] = np.asarray(env_outputs.reward)[2:]
        alt = alt._replace(
            reward=alt_rewards,
            observation=alt.observation._replace(frame=alt_frames))
        alt_actions = rng.integers(
            0, NUM_ACTIONS, (unroll_len, batch)).astype(np.int32)
        alt_actions[2:] = actions[2:]

        (_, _), state_a = agent.apply(
            params, actions, env_outputs, initial_state(batch))
        (_, _), state_b = agent.apply(
            params, alt_actions, alt, initial_state(batch))
        # Post-boundary inputs agree ⇒ final states agree despite different
        # pre-boundary history... but ONLY if done resets the core.
        np.testing.assert_allclose(state_a.h, state_b.h, rtol=1e-5, atol=1e-5)

        # Sanity: without the boundary the histories would diverge.
        no_done = np.zeros((unroll_len, batch), bool)
        (_, _), state_c = agent.apply(
            params, actions, env_outputs._replace(done=no_done),
            initial_state(batch))
        (_, _), state_d = agent.apply(
            params, alt_actions, alt._replace(done=no_done),
            initial_state(batch))
        assert not np.allclose(state_c.h, state_d.h, rtol=1e-5, atol=1e-5)

    def test_resnet_torso(self):
        agent, params = init_agent(torso_type="resnet")
        rng = np.random.default_rng(4)
        env_outputs = make_env_outputs(rng, 2, 2)
        actions = np.zeros((2, 2), np.int32)
        (logits, baseline), _ = agent.apply(
            params, actions, env_outputs, initial_state(2))
        assert logits.shape == (2, 2, NUM_ACTIONS)
        assert baseline.shape == (2, 2)

    def test_instruction_conditioning(self):
        agent, params = init_agent(use_instruction=True)
        rng = np.random.default_rng(5)
        env_outputs = make_env_outputs(rng, 2, 2, instruction=True)
        actions = np.zeros((2, 2), np.int32)
        (logits, _), _ = agent.apply(
            params, actions, env_outputs, initial_state(2))
        # Different instructions must change the policy.
        obs = env_outputs.observation
        other = env_outputs._replace(observation=obs._replace(
            instruction=np.asarray(obs.instruction) + 1))
        (logits2, _), _ = agent.apply(
            params, actions, other, initial_state(2))
        assert not np.allclose(logits, logits2)


class TestActorStep:
    def test_shapes_and_determinism(self):
        agent, params = init_agent()
        rng = np.random.default_rng(6)
        batch = 4
        env_outputs = make_env_outputs(rng, 1, batch)
        env_output = jax.tree_util.tree_map(
            lambda x: x[0] if x is not None else None,
            env_outputs, is_leaf=lambda x: x is None)
        out, state = actor_step(
            agent, params, jax.random.key(0),
            np.zeros((batch,), np.int32), env_output, initial_state(batch))
        assert out.action.shape == (batch,)
        assert out.action.dtype == jnp.int32
        assert out.policy_logits.shape == (batch, NUM_ACTIONS)
        assert out.baseline.shape == (batch,)
        assert state.c.shape == (batch, 256)
        # Same key ⇒ same sample; different key ⇒ (almost surely) may differ.
        out2, _ = actor_step(
            agent, params, jax.random.key(0),
            np.zeros((batch,), np.int32), env_output, initial_state(batch))
        np.testing.assert_array_equal(out.action, out2.action)

    def test_actions_within_range(self):
        agent, params = init_agent()
        rng = np.random.default_rng(7)
        batch = 8
        env_output = jax.tree_util.tree_map(
            lambda x: x[0] if x is not None else None,
            make_env_outputs(rng, 1, batch),
            is_leaf=lambda x: x is None)
        for seed in range(3):
            out, _ = actor_step(
                agent, params, jax.random.key(seed),
                np.zeros((batch,), np.int32), env_output,
                initial_state(batch))
            assert np.all((np.asarray(out.action) >= 0)
                          & (np.asarray(out.action) < NUM_ACTIONS))


class TestInstructionEncoder:
    def test_padding_is_ignored(self):
        enc = InstructionEncoder()
        ids = np.array([[3, 7, 0, 0]], np.int32)
        params = enc.init(jax.random.key(0), ids)
        out = enc.apply(params, ids)
        assert out.shape == (1, 64)
        # Changing only the padded tail must not change the encoding...
        ids_b = np.array([[3, 7, 0, 0]], np.int32)
        np.testing.assert_allclose(
            out, enc.apply(params, ids_b), rtol=1e-6)
        # ...while changing a real token must.
        ids_c = np.array([[3, 9, 0, 0]], np.int32)
        assert not np.allclose(out, enc.apply(params, ids_c))

    def test_hash_instruction(self):
        ids = hash_instruction("go to the red door")
        assert ids.shape == (16,)
        assert ids.dtype == np.int32
        assert np.all(ids[:5] > 0) and np.all(ids[5:] == 0)
        # Deterministic and word-order-sensitive.
        np.testing.assert_array_equal(ids, hash_instruction(
            "go to the red door"))
        assert not np.array_equal(ids, hash_instruction(
            "go to the blue door"))
        # Empty instruction (Doom/Atari path) is all padding.
        assert np.all(hash_instruction("") == 0)


class TestPallasCore:
    """The fused Pallas LSTM core (ops/lstm_pallas.py) must be a drop-in
    for the nn.scan path: identical param tree, identical init values,
    matching outputs and gradients on the same params."""

    def test_param_trees_identical(self):
        _, params_xla = init_agent(core_impl="xla")
        _, params_pal = init_agent(core_impl="pallas")
        flat_x = jax.tree_util.tree_flatten_with_path(params_xla)[0]
        flat_p = jax.tree_util.tree_flatten_with_path(params_pal)[0]
        assert [p for p, _ in flat_x] == [p for p, _ in flat_p]
        for (path, a), (_, b) in zip(flat_x, flat_p):
            assert a.shape == b.shape, path
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), err_msg=str(path),
                rtol=1e-6, atol=1e-7)

    def test_forward_parity_with_done_resets(self):
        agent_x, params = init_agent(core_impl="xla")
        agent_p = ImpalaAgent(num_actions=NUM_ACTIONS, core_impl="pallas")
        rng = np.random.default_rng(2)
        unroll_len, batch = 9, 4
        done = rng.random((unroll_len, batch)) < 0.3
        env_outputs = make_env_outputs(rng, unroll_len, batch, done=done)
        actions = rng.integers(0, NUM_ACTIONS, (unroll_len, batch)).astype(
            np.int32)
        state0 = initial_state(batch)
        (lx, bx), sx = agent_x.apply(params, actions, env_outputs, state0)
        (lp, bp), sp = agent_p.apply(params, actions, env_outputs, state0)
        np.testing.assert_allclose(np.asarray(lp), np.asarray(lx),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(bp), np.asarray(bx),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(sp.c), np.asarray(sx.c),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(sp.h), np.asarray(sx.h),
                                   rtol=1e-4, atol=1e-5)

    def test_grad_parity(self):
        agent_x, params = init_agent(core_impl="xla")
        agent_p = ImpalaAgent(num_actions=NUM_ACTIONS, core_impl="pallas")
        rng = np.random.default_rng(3)
        unroll_len, batch = 6, 3
        done = rng.random((unroll_len, batch)) < 0.2
        env_outputs = make_env_outputs(rng, unroll_len, batch, done=done)
        actions = rng.integers(0, NUM_ACTIONS, (unroll_len, batch)).astype(
            np.int32)
        state0 = initial_state(batch)

        def loss(agent):
            def fn(p):
                (logits, baseline), state = agent.apply(
                    p, actions, env_outputs, state0)
                return (jnp.sum(logits * logits) + jnp.sum(baseline)
                        + jnp.sum(state.c) + jnp.sum(state.h))
            return fn

        gx = jax.grad(loss(agent_x))(params)
        gp = jax.grad(loss(agent_p))(params)
        flat_x = jax.tree_util.tree_flatten_with_path(gx)[0]
        flat_p = jax.tree_util.tree_flatten_with_path(gp)[0]
        for (path, a), (_, b) in zip(flat_x, flat_p):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), err_msg=str(path),
                rtol=2e-3, atol=1e-4)

    def test_unknown_core_impl_raises(self):
        with pytest.raises(ValueError, match="core_impl"):
            init_agent(core_impl="bogus")

    def test_bf16_matmul_core_close_and_grads_finite(self):
        """core_matmul_dtype="bfloat16" (MXU mixed precision,
        ops/lstm_pallas.py) tracks the f32 core within bf16 rounding and
        keeps gradients finite — the opt-in knob behind the r3 MFU push
        (VERDICT item 7)."""
        agent_x, params = init_agent(core_impl="xla")
        agent_b = ImpalaAgent(num_actions=NUM_ACTIONS, core_impl="pallas",
                              core_matmul_dtype="bfloat16")
        rng = np.random.default_rng(4)
        unroll_len, batch = 7, 4
        done = rng.random((unroll_len, batch)) < 0.25
        env_outputs = make_env_outputs(rng, unroll_len, batch, done=done)
        actions = rng.integers(0, NUM_ACTIONS, (unroll_len, batch)).astype(
            np.int32)
        state0 = initial_state(batch)
        (lx, bx), sx = agent_x.apply(params, actions, env_outputs, state0)
        (lb, bb), sb = agent_b.apply(params, actions, env_outputs, state0)
        # bf16 operands: ~1e-2 relative tolerance (8-bit mantissa),
        # carries stay f32 so drift does not compound catastrophically.
        np.testing.assert_allclose(np.asarray(lb), np.asarray(lx),
                                   rtol=0.1, atol=0.05)
        np.testing.assert_allclose(np.asarray(bb), np.asarray(bx),
                                   rtol=0.1, atol=0.05)
        np.testing.assert_allclose(np.asarray(sb.c), np.asarray(sx.c),
                                   rtol=0.1, atol=0.05)
        np.testing.assert_allclose(np.asarray(sb.h), np.asarray(sx.h),
                                   rtol=0.1, atol=0.05)

        def loss(p):
            (logits, baseline), state = agent_b.apply(
                p, actions, env_outputs, state0)
            return jnp.sum(logits * logits) + jnp.sum(baseline)

        grads = jax.grad(loss)(params)
        for leaf in jax.tree_util.tree_leaves(grads):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_bad_matmul_dtype_raises(self):
        from scalable_agent_tpu.ops import lstm_pallas

        with pytest.raises(ValueError, match="matmul_dtype"):
            lstm_pallas.lstm_unroll(
                jnp.zeros((2, 2, 8), jnp.float32),
                jnp.zeros((2, 2), jnp.float32),
                jnp.zeros((2, 4), jnp.float32),
                jnp.zeros((2, 4), jnp.float32),
                jnp.zeros((8, 16), jnp.float32),
                jnp.zeros((4, 16), jnp.float32),
                jnp.zeros((16,), jnp.float32),
                True, "int8")
