"""Sequence-parallel V-trace: time-sharded recurrence == single-device.

SURVEY §5.7 promised the V-trace scan shardable over a mesh axis; this
proves it end-to-end on an 8-virtual-device CPU mesh (conftest sets
XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""

import numpy as np
import pytest

import jax

from scalable_agent_tpu.ops import vtrace
from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.parallel.sequence import (
    from_importance_weights_sharded,
)


def make_inputs(seq_len, batch, seed=0):
    rng = np.random.RandomState(seed)
    return dict(
        log_rhos=rng.uniform(-2.5, 2.5, (seq_len, batch)).astype(np.float32),
        discounts=(rng.uniform(0, 1, (seq_len, batch)) * 0.95)
        .astype(np.float32),
        rewards=rng.standard_normal((seq_len, batch)).astype(np.float32),
        values=rng.standard_normal((seq_len, batch)).astype(np.float32),
        bootstrap_value=rng.standard_normal((batch,)).astype(np.float32),
    )


@pytest.mark.parametrize("shards", [2, 4, 8])
def test_time_sharded_matches_single_device(shards):
    mesh = make_mesh(MeshSpec(data=shards, model=1),
                     devices=jax.devices()[:shards])
    inputs = make_inputs(96, 5)
    ref = vtrace.from_importance_weights(scan_impl="associative", **inputs)
    out = from_importance_weights_sharded(mesh, seq_axis="data", **inputs)
    np.testing.assert_allclose(
        np.asarray(out.vs), np.asarray(ref.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out.pg_advantages), np.asarray(ref.pg_advantages),
        rtol=1e-4, atol=1e-5)


def test_time_sharded_no_clipping_and_jit():
    mesh = make_mesh(MeshSpec(data=4, model=1), devices=jax.devices()[:4])
    inputs = make_inputs(64, 3, seed=1)
    ref = vtrace.from_importance_weights(
        clip_rho_threshold=None, clip_pg_rho_threshold=None, **inputs)

    @jax.jit
    def fn(log_rhos, discounts, rewards, values, bootstrap_value):
        return from_importance_weights_sharded(
            mesh, log_rhos, discounts, rewards, values, bootstrap_value,
            clip_rho_threshold=None, clip_pg_rho_threshold=None,
            seq_axis="data")

    out = fn(**inputs)
    np.testing.assert_allclose(
        np.asarray(out.vs), np.asarray(ref.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out.pg_advantages), np.asarray(ref.pg_advantages),
        rtol=1e-4, atol=1e-5)


def test_uneven_split_raises():
    mesh = make_mesh(MeshSpec(data=4, model=1), devices=jax.devices()[:4])
    inputs = make_inputs(10, 2)  # 10 % 4 != 0
    with pytest.raises(ValueError, match="divide evenly"):
        from_importance_weights_sharded(mesh, seq_axis="data", **inputs)


def test_from_importance_weights_dispatches_time_sharded():
    """ops/vtrace.from_importance_weights(scan_impl="time_sharded") is
    the config-reachable entry to the sharded recurrence."""
    mesh = make_mesh(MeshSpec(data=1, seq=4, model=1),
                     devices=jax.devices()[:4])
    inputs = make_inputs(32, 3, seed=2)
    ref = vtrace.from_importance_weights(scan_impl="associative", **inputs)
    out = vtrace.from_importance_weights(
        scan_impl="time_sharded", mesh=mesh, **inputs)
    np.testing.assert_allclose(
        np.asarray(out.vs), np.asarray(ref.vs), rtol=1e-4, atol=1e-5)
    with pytest.raises(ValueError, match="mesh"):
        vtrace.from_importance_weights(scan_impl="time_sharded", **inputs)


@pytest.mark.slow
class TestLearnerTimeSharded:
    """Full Learner.update on a (data=2, seq=2) mesh == the
    single-axis associative path (the SURVEY §5.7 hook, reachable from
    config via mesh_seq/scan_impl — VERDICT r3 item 4)."""

    def test_update_parity(self):
        import functools

        from scalable_agent_tpu.envs import MultiEnv, make_impala_stream
        from scalable_agent_tpu.envs.spec import TensorSpec
        from scalable_agent_tpu.models import ImpalaAgent
        from scalable_agent_tpu.runtime import (
            Learner, LearnerHyperparams, Trajectory, VectorActor)

        T, B = 8, 8
        frame = TensorSpec((16, 16, 3), np.uint8, "frame")
        agent = ImpalaAgent(num_actions=4)
        fns = [functools.partial(make_impala_stream, "fake_small",
                                 seed=i, num_actions=4)
               for i in range(B)]
        envs = MultiEnv(fns, frame, num_workers=2)
        try:
            mesh_flat = make_mesh(MeshSpec(data=4),
                                  devices=jax.devices()[:4])
            mesh_seq = make_mesh(MeshSpec(data=2, seq=2),
                                 devices=jax.devices()[:4])
            hp = LearnerHyperparams(total_environment_frames=1e6)
            ref = Learner(agent, hp, mesh_flat, frames_per_update=T * B,
                          scan_impl="associative")
            sharded = Learner(agent, hp, mesh_seq, frames_per_update=T * B)
            assert sharded._scan_impl == "time_sharded"  # auto-selected

            actor = VectorActor(agent, envs, T, seed=3)
            actor._bootstrap(None)
            params = agent.init(
                jax.random.key(0),
                np.asarray(agent.zero_actions(B))[None],
                jax.tree_util.tree_map(
                    lambda x: None if x is None else np.asarray(x)[None],
                    actor._last_env_output, is_leaf=lambda x: x is None),
                actor._core_state)
            out = actor.run_unroll(params)
            traj = Trajectory(out.agent_state, out.env_outputs,
                              out.agent_outputs)

            state_ref = ref.init(jax.random.key(1), traj)
            state_sh = sharded.init(jax.random.key(1), traj)
            state_ref, metrics_ref = ref.update(
                state_ref, ref.put_trajectory(traj))
            state_sh, metrics_sh = sharded.update(
                state_sh, sharded.put_trajectory(traj))

            for key in ("total_loss", "policy_gradient_loss",
                        "baseline_loss", "entropy_loss", "grad_norm"):
                np.testing.assert_allclose(
                    float(metrics_ref[key]), float(metrics_sh[key]),
                    rtol=2e-4, err_msg=key)
            # Updated params agree leaf-by-leaf.
            flat_ref = jax.tree_util.tree_leaves(state_ref.params)
            flat_sh = jax.tree_util.tree_leaves(state_sh.params)
            for a, b in zip(flat_ref, flat_sh):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)
        finally:
            envs.close()
