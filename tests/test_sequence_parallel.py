"""Sequence-parallel V-trace: time-sharded recurrence == single-device.

SURVEY §5.7 promised the V-trace scan shardable over a mesh axis; this
proves it end-to-end on an 8-virtual-device CPU mesh (conftest sets
XLA_FLAGS=--xla_force_host_platform_device_count=8).
"""

import numpy as np
import pytest

import jax

from scalable_agent_tpu.ops import vtrace
from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.parallel.sequence import (
    from_importance_weights_sharded,
)


def make_inputs(seq_len, batch, seed=0):
    rng = np.random.RandomState(seed)
    return dict(
        log_rhos=rng.uniform(-2.5, 2.5, (seq_len, batch)).astype(np.float32),
        discounts=(rng.uniform(0, 1, (seq_len, batch)) * 0.95)
        .astype(np.float32),
        rewards=rng.standard_normal((seq_len, batch)).astype(np.float32),
        values=rng.standard_normal((seq_len, batch)).astype(np.float32),
        bootstrap_value=rng.standard_normal((batch,)).astype(np.float32),
    )


@pytest.mark.parametrize("shards", [2, 4, 8])
def test_time_sharded_matches_single_device(shards):
    mesh = make_mesh(MeshSpec(data=shards, model=1),
                     devices=jax.devices()[:shards])
    inputs = make_inputs(96, 5)
    ref = vtrace.from_importance_weights(scan_impl="associative", **inputs)
    out = from_importance_weights_sharded(mesh, seq_axis="data", **inputs)
    np.testing.assert_allclose(
        np.asarray(out.vs), np.asarray(ref.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out.pg_advantages), np.asarray(ref.pg_advantages),
        rtol=1e-4, atol=1e-5)


def test_time_sharded_no_clipping_and_jit():
    mesh = make_mesh(MeshSpec(data=4, model=1), devices=jax.devices()[:4])
    inputs = make_inputs(64, 3, seed=1)
    ref = vtrace.from_importance_weights(
        clip_rho_threshold=None, clip_pg_rho_threshold=None, **inputs)

    @jax.jit
    def fn(log_rhos, discounts, rewards, values, bootstrap_value):
        return from_importance_weights_sharded(
            mesh, log_rhos, discounts, rewards, values, bootstrap_value,
            clip_rho_threshold=None, clip_pg_rho_threshold=None,
            seq_axis="data")

    out = fn(**inputs)
    np.testing.assert_allclose(
        np.asarray(out.vs), np.asarray(ref.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out.pg_advantages), np.asarray(ref.pg_advantages),
        rtol=1e-4, atol=1e-5)


def test_uneven_split_raises():
    mesh = make_mesh(MeshSpec(data=4, model=1), devices=jax.devices()[:4])
    inputs = make_inputs(10, 2)  # 10 % 4 != 0
    with pytest.raises(ValueError, match="divide evenly"):
        from_importance_weights_sharded(mesh, seq_axis="data", **inputs)
