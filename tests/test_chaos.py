"""ISSUE 4: the self-healing training loop, driven by deterministic
fault injection (``runtime/faults.py``).

Everything here carries the ``chaos`` marker.  The fast deterministic
subset (injector grammar, the learner's fused non-finite guard,
checkpoint integrity + walk-back, actor retry, driver rollback/exit-71)
is tier-1; the full four-fault driver soak + torn-checkpoint resume is
additionally marked ``slow``.
"""

import dataclasses
import functools
import json
import os
import time

import jax
import numpy as np
import pytest

from scalable_agent_tpu.config import Config
from scalable_agent_tpu.driver import train as run_train
from scalable_agent_tpu.driver import zero_trajectory
from scalable_agent_tpu.envs import MultiEnv, make_impala_stream
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.models import ImpalaAgent
from scalable_agent_tpu.models import agent as agent_mod
from scalable_agent_tpu.obs import get_flight_recorder, get_registry
from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.runtime import (
    ActorPool,
    FaultInjector,
    InjectedFault,
    Learner,
    LearnerHyperparams,
    NonFiniteTracker,
    configure_faults,
    get_fault_injector,
)
from scalable_agent_tpu.runtime.checkpoint import CheckpointManager
from scalable_agent_tpu.runtime.faults import (
    CHANNEL_NAME,
    CHANNEL_POLL_S,
    parse_chaos_spec,
    parse_chaos_spec_full,
)

pytestmark = pytest.mark.chaos

NUM_ACTIONS = 4
FRAME = TensorSpec((8, 8, 3), np.uint8, "frame")


class _ObsSpec:
    frame = FRAME
    instruction = None
    measurements = None


def _counter_value(name: str) -> float:
    return float(get_registry().snapshot().get(name, 0.0))


@pytest.fixture(autouse=True)
def _clean_faults():
    """No chaos spec may leak between tests (the injector is a process
    global, like the other obs singletons)."""
    configure_faults("")
    yield
    configure_faults("")


@pytest.fixture(scope="module")
def learner_setup():
    agent = ImpalaAgent(num_actions=NUM_ACTIONS)
    traj = zero_trajectory(Config(), _ObsSpec, agent, batch=4)
    mesh = make_mesh(MeshSpec(data=4, model=1), devices=jax.devices()[:4])
    learner = Learner(
        agent, LearnerHyperparams(total_environment_frames=1e6), mesh,
        frames_per_update=16)
    return learner, traj


def _nan_trajectory(traj):
    return traj._replace(env_outputs=traj.env_outputs._replace(
        reward=traj.env_outputs.reward + np.float32("nan")))


# ---------------------------------------------------------------------------
# Fault injector
# ---------------------------------------------------------------------------


class TestFaultInjector:
    def test_grammar(self):
        points = parse_chaos_spec(
            "nan_grad@7;actor_raise@3:12;ckpt_torn@1;worker_kill@20")
        assert points == {
            "nan_grad": frozenset({7}),
            "actor_raise": frozenset({3, 12}),
            "ckpt_torn": frozenset({1}),
            "worker_kill": frozenset({20}),
        }
        # Duplicate points merge; empty entries/spec are fine.
        assert parse_chaos_spec("p@1;p@3")["p"] == frozenset({1, 3})
        assert parse_chaos_spec("") == {}
        assert parse_chaos_spec(" ; ") == {}

    @pytest.mark.parametrize("bad", ["p", "p@", "p@0", "p@1:,2", "@3",
                                     "p@x", "p@1 2"])
    def test_malformed_spec_raises(self, bad):
        with pytest.raises(ValueError, match="chaos_spec"):
            parse_chaos_spec(bad)

    def test_occurrence_firing_is_deterministic(self):
        injector = FaultInjector("p@2:4")
        fired = [injector.should_fire("p") for _ in range(6)]
        assert fired == [False, True, False, True, False, False]
        # A fresh injector with the same spec replays identically.
        again = FaultInjector("p@2:4")
        assert [again.should_fire("p") for _ in range(6)] == fired

    def test_maybe_raise(self):
        injector = FaultInjector("boom@1")
        with pytest.raises(InjectedFault, match="boom"):
            injector.maybe_raise("boom")
        injector.maybe_raise("boom")  # occurrence 2: no raise
        assert injector.counts() == {"boom": 2}

    def test_unconfigured_point_never_fires(self):
        injector = FaultInjector("other@1")
        assert not injector.should_fire("p")

    def test_disabled_injector_is_inert(self):
        injector = configure_faults("")
        assert not injector.active
        assert not injector.should_fire("anything")
        assert injector.counts() == {}

    def test_configure_installs_global(self):
        injector = configure_faults("p@1")
        assert get_fault_injector() is injector
        configure_faults("")
        assert not get_fault_injector().active


class TestTriggerForms:
    """ISSUE 20: the ``@t=`` and ``@p=`` trigger forms of the grammar
    (the soak engine's schedule grammar shares them)."""

    def test_full_grammar_parses_every_form(self):
        parsed = parse_chaos_spec_full(
            "nan_grad@7;ckpt_torn@t=5s;worker_kill@t=1.5;"
            "actor_raise@p=0.25")
        assert parsed.occurrences == {"nan_grad": frozenset({7})}
        assert parsed.at_times == {"ckpt_torn": (5.0,),
                                   "worker_kill": (1.5,)}
        assert parsed.probs == {"actor_raise": 0.25}

    def test_duplicate_time_triggers_merge_sorted(self):
        parsed = parse_chaos_spec_full("p@t=5;p@t=2s")
        assert parsed.at_times["p"] == (2.0, 5.0)

    def test_occurrence_view_validates_but_drops_other_forms(self):
        # In-graph consumers bake occurrence sets into compiled
        # programs; time/probability entries still parse (a typo must
        # not be silently dropped) but contribute no indices.
        assert parse_chaos_spec("p@t=5;q@p=0.5;r@3") == {
            "r": frozenset({3})}

    @pytest.mark.parametrize("bad", ["p@t=", "p@p=", "p@t=5x",
                                     "p@p=0", "p@p=1.5"])
    def test_malformed_trigger_forms_raise(self, bad):
        with pytest.raises(ValueError, match="chaos_spec"):
            parse_chaos_spec_full(bad)

    def test_time_trigger_fires_once_when_due(self):
        injector = FaultInjector("p@t=0")
        assert [injector.should_fire("p") for _ in range(3)] == [
            True, False, False]

    def test_time_trigger_not_yet_due_never_fires(self):
        injector = FaultInjector("p@t=9999")
        assert not any(injector.should_fire("p") for _ in range(3))

    def test_stacked_time_triggers_fire_one_each(self):
        injector = FaultInjector("p@t=0;p@t=0s")
        assert [injector.should_fire("p") for _ in range(3)] == [
            True, True, False]

    def test_probability_trigger_replays_per_seed(self):
        a = FaultInjector("p@p=0.5", seed=7)
        b = FaultInjector("p@p=0.5", seed=7)
        seq = [a.should_fire("p") for _ in range(32)]
        assert [b.should_fire("p") for _ in range(32)] == seq
        assert any(seq) and not all(seq)

    def test_probability_one_always_fires(self):
        injector = FaultInjector("p@p=1.0", seed=3)
        assert all(injector.should_fire("p") for _ in range(5))


class TestRuntimeChannel:
    """ISSUE 20: the ``<logdir>/chaos_inject.jsonl`` runtime injection
    channel — faults landing in an already-running process."""

    @staticmethod
    def _arm(path, point, **extra):
        payload = {"point": point, "t_unix": time.time(), **extra}
        with open(path, "a") as f:
            f.write(json.dumps(payload) + "\n")

    @pytest.fixture
    def channel(self, tmp_path):
        return str(tmp_path / CHANNEL_NAME)

    def test_channel_only_injector_is_active(self, channel):
        assert FaultInjector("", channel_path=channel).active

    def test_line_arms_exactly_one_firing(self, channel):
        injector = FaultInjector("", channel_path=channel)
        self._arm(channel, "p")
        assert injector.should_fire("p")
        assert not injector.should_fire("p")

    def test_count_field_arms_multiple_firings(self, channel):
        injector = FaultInjector("", channel_path=channel)
        self._arm(channel, "p", count=3)
        assert [injector.should_fire("p") for _ in range(4)] == [
            True, True, True, False]

    def test_stale_line_from_a_dead_epoch_is_skipped(self, channel):
        injector = FaultInjector("", channel_path=channel)
        # A relaunched fleet epoch must not re-fire injections the dead
        # epoch already consumed: t_unix predates this injector's arm.
        self._arm(channel, "p")
        with open(channel, "w") as f:
            f.write(json.dumps(
                {"point": "p", "t_unix": time.time() - 100.0}) + "\n")
        assert not injector.should_fire("p")

    def test_proc_targeting_matches_process_id(self, channel):
        injector = FaultInjector("", channel_path=channel,
                                 process_id=1)
        self._arm(channel, "p", proc=0)
        self._arm(channel, "p", proc=1)
        # One poll consumes both lines; only the proc=1 arm is ours.
        assert injector.should_fire("p")
        assert not injector.should_fire("p")

    def test_torn_final_line_is_deferred_not_dropped(self, channel):
        injector = FaultInjector("", channel_path=channel)
        payload = json.dumps({"point": "p", "t_unix": time.time()})
        with open(channel, "w") as f:
            f.write(payload[:10])  # crash-mid-append stand-in
        assert not injector.should_fire("p")
        with open(channel, "a") as f:
            f.write(payload[10:] + "\n")
        time.sleep(CHANNEL_POLL_S + 0.05)  # past the poll gate
        assert injector.should_fire("p")

    def test_garbage_lines_are_ignored(self, channel):
        injector = FaultInjector("", channel_path=channel)
        with open(channel, "w") as f:
            f.write("not json\n")
            f.write(json.dumps({"nope": 1}) + "\n")
        self._arm(channel, "p")
        assert injector.should_fire("p")
        assert not injector.should_fire("p")


# ---------------------------------------------------------------------------
# Learner non-finite guard
# ---------------------------------------------------------------------------


class TestNonFiniteGuard:
    def test_nan_batch_is_skipped_params_held_frames_exact(
            self, learner_setup):
        learner, traj = learner_setup
        state = learner.init(jax.random.key(0), traj)
        state, m = learner.update(state, learner.put_trajectory(traj))
        assert float(np.asarray(m["update_skipped"])) == 0.0
        # Host copies BEFORE the next update: the jitted update donates
        # its state argument.
        params_before = jax.tree_util.tree_map(
            lambda x: np.asarray(x).copy(), state.params)
        opt_before = jax.tree_util.tree_map(
            lambda x: np.asarray(x).copy(), state.opt_state)
        frames_before = float(np.asarray(state.env_frames))

        bad = learner.put_trajectory(_nan_trajectory(traj))
        state, m = learner.update(state, bad)
        assert float(np.asarray(m["update_skipped"])) == 1.0
        assert float(np.asarray(m["nonfinite_streak"])) == 1.0
        # params/opt_state are bit-for-bit unchanged...
        for before, after in zip(
                jax.tree_util.tree_leaves(params_before),
                jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(before, np.asarray(after))
        for before, after in zip(
                jax.tree_util.tree_leaves(opt_before),
                jax.tree_util.tree_leaves(state.opt_state)):
            np.testing.assert_array_equal(before, np.asarray(after))
        # ...but frame accounting still retired the batch, exactly.
        assert float(np.asarray(state.env_frames)) == frames_before + 16

    def test_streak_resets_on_finite_update(self, learner_setup):
        learner, traj = learner_setup
        state = learner.init(jax.random.key(1), traj)
        bad = learner.put_trajectory(_nan_trajectory(traj))
        state, m = learner.update(state, bad)
        bad = learner.put_trajectory(_nan_trajectory(traj))
        state, m = learner.update(state, bad)
        assert float(np.asarray(m["nonfinite_streak"])) == 2.0
        assert float(np.asarray(m["nonfinite_skips"])) == 2.0
        state, m = learner.update(state, learner.put_trajectory(traj))
        assert float(np.asarray(m["nonfinite_streak"])) == 0.0
        # Cumulative count survives the recovery.
        assert float(np.asarray(m["nonfinite_skips"])) == 2.0

    def test_nan_grad_injection_point(self, learner_setup):
        learner, traj = learner_setup
        state = learner.init(jax.random.key(2), traj)
        configure_faults("nan_grad@2")
        state, m = learner.update(state, learner.put_trajectory(traj))
        assert float(np.asarray(m["update_skipped"])) == 0.0
        state, m = learner.update(state, learner.put_trajectory(traj))
        assert float(np.asarray(m["update_skipped"])) == 1.0

    def test_replay_corrupt_is_absorbed_as_noop_and_attributed(
            self, learner_setup):
        """ISSUE 13 satellite: the ``replay_corrupt`` chaos point
        (runtime/replay.py) poisons one SAMPLED batch's rewards with
        NaN — the fused non-finite guard must absorb the replayed
        update as a bit-exact no-op (params/opt_state held, env_frames
        held because the update is replayed) and the skip counter must
        attribute it."""
        from scalable_agent_tpu.runtime import DeviceReplayBuffer

        learner, traj = learner_setup
        state = learner.init(jax.random.key(3), traj)
        state, m = learner.update(state, learner.put_trajectory(traj))
        assert float(np.asarray(m["update_skipped"])) == 0.0
        params_before = jax.tree_util.tree_map(
            lambda x: np.asarray(x).copy(), state.params)
        frames_before = float(np.asarray(state.env_frames))

        replay = DeviceReplayBuffer(2, seed=0)
        replay.insert(learner.put_trajectory(traj))
        configure_faults("replay_corrupt@2")
        clean = replay.sample()    # occurrence 1: not armed
        assert np.all(np.isfinite(np.asarray(clean.env_outputs.reward)))
        poisoned = replay.sample()  # occurrence 2: fires
        assert not np.all(np.isfinite(
            np.asarray(poisoned.env_outputs.reward)))

        state, m = learner.update(state, poisoned, fresh=False)
        assert float(np.asarray(m["update_skipped"])) == 1.0
        assert float(np.asarray(m["nonfinite_streak"])) == 1.0
        for before, after in zip(
                jax.tree_util.tree_leaves(params_before),
                jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(before, np.asarray(after))
        # Replayed: the frame counter is held even on the skip path.
        assert float(np.asarray(state.env_frames)) == frames_before

    def test_guard_can_be_disabled(self, learner_setup):
        _, traj = learner_setup
        agent = ImpalaAgent(num_actions=NUM_ACTIONS)
        mesh = make_mesh(MeshSpec(data=4, model=1),
                         devices=jax.devices()[:4])
        learner = Learner(
            agent, LearnerHyperparams(total_environment_frames=1e6),
            mesh, frames_per_update=16, finite_guard=False)
        state = learner.init(jax.random.key(0), traj)
        state, m = learner.update(
            state, learner.put_trajectory(_nan_trajectory(traj)))
        assert "update_skipped" not in m
        # Unguarded, the NaN poisons the params — the behavior the
        # guard exists to prevent.
        leaf = np.asarray(jax.tree_util.tree_leaves(state.params)[0])
        assert not np.all(np.isfinite(leaf))


class TestNonFiniteTracker:
    def test_counts_deltas_and_exhaustion(self):
        tracker = NonFiniteTracker(tolerance=3)
        before = _counter_value("learner/nonfinite_skips_total")
        assert not tracker.observe(
            {"nonfinite_skips": 2.0, "nonfinite_streak": 2.0})
        assert _counter_value(
            "learner/nonfinite_skips_total") == before + 2.0
        # Same cumulative value again: no double count.
        assert not tracker.observe(
            {"nonfinite_skips": 2.0, "nonfinite_streak": 2.0})
        assert _counter_value(
            "learner/nonfinite_skips_total") == before + 2.0
        assert tracker.observe(
            {"nonfinite_skips": 3.0, "nonfinite_streak": 3.0})

    def test_rebase_after_rollback(self):
        tracker = NonFiniteTracker(tolerance=2)
        before = _counter_value("learner/nonfinite_skips_total")
        tracker.observe({"nonfinite_skips": 5.0, "nonfinite_streak": 2.0})
        tracker.rebase(1.0)  # restored checkpoint carries 1 skip
        tracker.observe({"nonfinite_skips": 2.0, "nonfinite_streak": 1.0})
        assert _counter_value(
            "learner/nonfinite_skips_total") == before + 6.0

    def test_zero_tolerance_disables_policy(self):
        tracker = NonFiniteTracker(tolerance=0)
        assert not tracker.observe(
            {"nonfinite_skips": 99.0, "nonfinite_streak": 99.0})


# ---------------------------------------------------------------------------
# Checkpoint integrity
# ---------------------------------------------------------------------------


@pytest.fixture()
def ckpt_setup(tmp_path, learner_setup):
    learner, traj = learner_setup
    state = learner.init(jax.random.key(0), traj)
    ckpt = CheckpointManager(str(tmp_path), interval_s=0.0, keep=5)
    yield ckpt, learner, state, traj
    ckpt.close()


class TestCheckpointIntegrity:
    def test_manifest_written_and_clean_restore(self, ckpt_setup):
        ckpt, learner, state, traj = ckpt_setup
        assert ckpt.maybe_save(1, state)
        ckpt.wait()
        manifest_dir = os.path.join(ckpt._dir, "manifests")
        assert os.path.exists(os.path.join(manifest_dir, "1.json"))
        template = learner.init(jax.random.key(0), traj)
        step, restored = ckpt.restore(target=template)
        assert step == 1
        for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                        jax.tree_util.tree_leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_torn_latest_walks_back(self, ckpt_setup):
        ckpt, learner, state, traj = ckpt_setup
        ckpt.maybe_save(1, state)
        state, _ = learner.update(state, learner.put_trajectory(traj))
        ckpt.maybe_save(2, state)
        ckpt.wait()
        before = _counter_value("checkpoint/restore_fallbacks_total")
        ckpt._tear_step(2)
        template = learner.init(jax.random.key(0), traj)
        step, restored = ckpt.restore(target=template)
        assert step == 1
        assert _counter_value(
            "checkpoint/restore_fallbacks_total") == before + 1
        kinds = {e["kind"] for e in get_flight_recorder().snapshot()}
        assert "ckpt_fallback" in kinds
        # The torn newer step was quarantined: were it left as
        # latest_step, Orbax would silently skip (save() -> False)
        # every resumed save at a step <= 2 — including a final forced
        # one — while the manifest got rewritten for data never
        # written.
        assert ckpt.latest_verified_step() == 1
        ckpt._last_save = 0.0
        assert ckpt.maybe_save(2, state, force=True)
        ckpt.wait()
        step, _ = ckpt.restore(target=template)
        assert step == 2  # the re-save really landed on disk

    def test_every_step_torn_raises_loudly(self, ckpt_setup):
        """When retained steps exist but NONE verifies, restore must
        raise rather than return None — a silent fresh start would
        retrain into the logdir and let rotation delete the evidence."""
        from scalable_agent_tpu.runtime.checkpoint import (
            CheckpointIntegrityError,
        )

        ckpt, learner, state, traj = ckpt_setup
        ckpt.maybe_save(1, state)
        ckpt.maybe_save(2, state, force=True)
        ckpt.wait()
        ckpt._tear_step(1)
        ckpt._tear_step(2)
        template = learner.init(jax.random.key(0), traj)
        with pytest.raises(CheckpointIntegrityError,
                           match="none restored"):
            ckpt.restore(target=template)

    def test_legacy_pre_guard_checkpoint_migrates(self, ckpt_setup):
        """A checkpoint saved with the 3-field pre-guard TrainState must
        restore (guard counters zero-filled), not read as torn."""
        import typing

        import orbax.checkpoint as ocp

        class LegacyTrainState(typing.NamedTuple):  # the pre-PR layout
            params: object
            opt_state: object
            env_frames: object

        ckpt, learner, state, traj = ckpt_setup
        legacy = LegacyTrainState(
            params=jax.tree_util.tree_map(np.asarray, state.params),
            opt_state=jax.tree_util.tree_map(
                np.asarray, state.opt_state),
            env_frames=np.asarray(128.0, np.float32),
        )
        ckpt._manager.save(7, args=ocp.args.StandardSave(legacy))
        ckpt.wait()
        template = learner.init(jax.random.key(0), traj)
        step, restored = ckpt.restore(target=template)
        assert step == 7
        assert float(np.asarray(restored.env_frames)) == 128.0
        assert float(np.asarray(restored.nonfinite_skips)) == 0.0
        # The migrated state places back onto the mesh cleanly.
        placed = learner.place_state(restored)
        assert float(np.asarray(placed.nonfinite_streak)) == 0.0

    def test_missing_manifest_is_accepted(self, ckpt_setup):
        """Checkpoints written before the manifest existed must still
        restore (legacy acceptance)."""
        ckpt, learner, state, traj = ckpt_setup
        ckpt.maybe_save(1, state)
        ckpt.wait()
        os.remove(os.path.join(ckpt._dir, "manifests", "1.json"))
        template = learner.init(jax.random.key(0), traj)
        step, _ = ckpt.restore(target=template)
        assert step == 1

    def test_save_failure_degrades_then_forced_reraises(self, ckpt_setup):
        ckpt, learner, state, traj = ckpt_setup
        before = _counter_value("checkpoint/save_failures_total")
        configure_faults("ckpt_save_fail@1:2")
        assert not ckpt.maybe_save(1, state)
        assert _counter_value(
            "checkpoint/save_failures_total") == before + 1
        # The failed cadenced save backs off a full interval but does
        # not poison later saves...
        ckpt._last_save = 0.0
        with pytest.raises(InjectedFault):
            ckpt.maybe_save(2, state, force=True)  # ...forced re-raises
        configure_faults("")
        ckpt._last_save = 0.0
        assert ckpt.maybe_save(3, state)

    def test_ckpt_torn_injection_corrupts_on_disk(self, ckpt_setup):
        ckpt, learner, state, traj = ckpt_setup
        ckpt.maybe_save(1, state)
        configure_faults("ckpt_torn@1")
        ckpt._last_save = 0.0
        ckpt.maybe_save(2, state)
        configure_faults("")
        template = learner.init(jax.random.key(0), traj)
        step, _ = ckpt.restore(target=template)
        assert step == 1


# ---------------------------------------------------------------------------
# Actor retry
# ---------------------------------------------------------------------------


def _make_envs(n=2, workers=1):
    fns = [functools.partial(
        make_impala_stream, "fake_small", seed=i, height=8, width=8,
        num_actions=NUM_ACTIONS, episode_length=3) for i in range(n)]
    return MultiEnv(fns, FRAME, num_workers=workers)


def _make_pool(envs, **kwargs):
    agent = ImpalaAgent(num_actions=NUM_ACTIONS)
    out0 = envs.initial()
    batch = envs.num_envs
    params = agent.init(
        jax.random.key(0),
        np.zeros((1, batch), np.int32),
        jax.tree_util.tree_map(
            lambda x: None if x is None else np.asarray(x)[None],
            out0, is_leaf=lambda x: x is None),
        agent_mod.initial_state(batch))
    kwargs.setdefault("restart_backoff_s", 0.01)
    pool = ActorPool(agent, [envs], unroll_length=3, seed=1, **kwargs)
    pool.set_params(params)
    return pool


class TestActorRetry:
    def test_transient_raise_is_retried(self):
        envs = _make_envs()
        pool = _make_pool(envs, max_restarts=2)
        before = _counter_value("actor/restarts_total")
        configure_faults("actor_raise@1")
        pool.start()
        try:
            out = pool.get_trajectory(timeout=120)
            assert out.env_outputs.reward.shape == (4, 2)
            assert _counter_value("actor/restarts_total") == before + 1
            kinds = {e["kind"]
                     for e in get_flight_recorder().snapshot()}
            assert "actor_restart" in kinds
        finally:
            pool.stop()

    def test_budget_exhaustion_surfaces_terminal_failure(self):
        envs = _make_envs()
        pool = _make_pool(envs, max_restarts=1)
        configure_faults("actor_raise@1:2")
        pool.start()
        try:
            with pytest.raises(InjectedFault):
                pool.get_trajectory(timeout=120)
        finally:
            pool.stop()

    def test_zero_budget_fails_fast(self):
        envs = _make_envs()
        pool = _make_pool(envs, max_restarts=0)
        configure_faults("actor_raise@1")
        pool.start()
        try:
            with pytest.raises(InjectedFault):
                pool.get_trajectory(timeout=120)
        finally:
            pool.stop()

    def test_restarts_outside_window_do_not_exhaust_budget(self):
        """The budget detects crash loops, not lifetime faults (same
        semantics as MultiEnv's respawn window): raises spaced wider
        than the window never add up to a kill."""
        envs = _make_envs()
        # Backoff (0.1s) > window (0.05s): by the time the next raise
        # can occur the previous restart has aged out of the window.
        pool = _make_pool(envs, max_restarts=1, restart_backoff_s=0.1,
                          restart_window_s=0.05)
        before = _counter_value("actor/restarts_total")
        configure_faults("actor_raise@1:3:5")
        pool.start()
        try:
            for _ in range(3):
                out = pool.get_trajectory(timeout=120)
                assert not isinstance(out, Exception)
            assert _counter_value("actor/restarts_total") == before + 3
        finally:
            pool.stop()

    def test_worker_kill_respawns_and_counts(self):
        envs = _make_envs(n=2, workers=1)
        pool = _make_pool(envs, max_restarts=2)
        before = _counter_value("env/worker_respawns_total")
        configure_faults("worker_kill@2")
        pool.start()
        try:
            for _ in range(4):
                pool.get_trajectory(timeout=120)
            assert envs.total_respawns >= 1
            assert _counter_value(
                "env/worker_respawns_total") >= before + 1
            kinds = {e["kind"]
                     for e in get_flight_recorder().snapshot()}
            assert "worker_respawn" in kinds
        finally:
            pool.stop()


# ---------------------------------------------------------------------------
# Driver: rollback + exit 71 (tier-1 acceptance), four-fault soak (slow)
# ---------------------------------------------------------------------------


def _chaos_config(tmp_path, **overrides) -> Config:
    defaults = dict(
        mode="train",
        logdir=str(tmp_path / "run"),
        level_name="fake_small",
        num_actors=4,
        batch_size=2,
        unroll_length=4,
        num_action_repeats=1,
        total_environment_frames=40,  # 5 updates of 8 frames
        height=16,
        width=16,
        num_env_workers_per_group=2,
        compute_dtype="float32",
        checkpoint_interval_s=0.0,  # save every update
        log_interval_s=0.0,  # observe guard metrics every update
        seed=5,
    )
    defaults.update(overrides)
    return Config(**defaults)


class TestDriverRollback:
    def test_consecutive_skips_roll_back_and_train_completes(
            self, tmp_path):
        config = _chaos_config(
            tmp_path, total_environment_frames=48,
            chaos_spec="nan_grad@3:4", nonfinite_tolerance=2)
        skips_before = _counter_value("learner/nonfinite_skips_total")
        rollbacks_before = _counter_value("learner/rollbacks_total")
        metrics = run_train(config)
        assert metrics["env_frames"] == 48
        assert np.isfinite(metrics["total_loss"])
        assert _counter_value(
            "learner/nonfinite_skips_total") == skips_before + 2
        assert _counter_value(
            "learner/rollbacks_total") == rollbacks_before + 1
        kinds = {e["kind"] for e in get_flight_recorder().snapshot()}
        assert "rollback" in kinds and "nonfinite_skip" in kinds

    def test_no_rollback_exits_71(self, tmp_path):
        config = _chaos_config(
            tmp_path, chaos_spec="nan_grad@2:3",
            nonfinite_tolerance=2, no_rollback=True)
        with pytest.raises(SystemExit) as excinfo:
            run_train(config)
        assert excinfo.value.code == 71
        # The forensic dump fired before the exit.
        recorder = get_flight_recorder()
        assert recorder.last_dump_reason == "nonfinite:no_rollback"


@pytest.mark.slow
class TestChaosSoak:
    def test_four_fault_soak_then_torn_resume(self, tmp_path):
        """ISSUE 4 acceptance: ONE driver run injecting a NaN grad, a
        transient actor exception, a SIGKILL'd env worker, and a torn
        latest checkpoint trains to completion; the follow-up run
        resumes from the older valid checkpoint — with each recovery
        visible as its counter + flight-recorder event."""
        # 5 updates of 8 frames; saves fire per update (interval 0) so
        # the save sequence is steps 1..5 then the forced final at step
        # 5 again — ckpt_torn@6 tears the LATEST retained step.
        config = _chaos_config(
            tmp_path,
            chaos_spec=("nan_grad@2;actor_raise@1;worker_kill@3;"
                        "ckpt_torn@6"),
            actor_max_restarts=2)
        before = {
            name: _counter_value(name) for name in (
                "learner/nonfinite_skips_total",
                "actor/restarts_total",
                "env/worker_respawns_total",
                "faults/injected_total",
            )}
        metrics = run_train(config)
        assert metrics["env_frames"] == 40
        assert np.isfinite(metrics["total_loss"])
        assert _counter_value("learner/nonfinite_skips_total") == (
            before["learner/nonfinite_skips_total"] + 1)
        assert _counter_value("actor/restarts_total") == (
            before["actor/restarts_total"] + 1)
        assert _counter_value("env/worker_respawns_total") >= (
            before["env/worker_respawns_total"] + 1)
        assert _counter_value("faults/injected_total") == (
            before["faults/injected_total"] + 4)
        kinds = {e["kind"] for e in get_flight_recorder().snapshot()}
        assert {"fault", "nonfinite_skip", "actor_restart",
                "worker_respawn"} <= kinds

        # Resume on the same logdir: the torn latest step must be
        # rejected and the older valid step restored.
        fallbacks_before = _counter_value(
            "checkpoint/restore_fallbacks_total")
        config2 = dataclasses.replace(
            config, total_environment_frames=56.0, chaos_spec="")
        metrics2 = run_train(config2)
        assert metrics2["env_frames"] == 56
        assert _counter_value("checkpoint/restore_fallbacks_total") == (
            fallbacks_before + 1)
        # The walk-back landed one step below the torn latest (5 -> 4).
        assert _counter_value("checkpoint/restored_step") == 4.0
        kinds2 = {e["kind"] for e in get_flight_recorder().snapshot()}
        assert "ckpt_fallback" in kinds2
