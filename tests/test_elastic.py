"""Elastic fleet membership — tier-1 units (ISSUE 6).

The whole supervisor state machine runs here against scripted fake
workers and a virtual clock (launch -> reshard -> rejoin -> done, the
restart budget, backoff, MTTR measurement, verdict-file consumption),
plus the fleet monitor's membership-verdict writes, the kv_suspect
early forensic dump, the SIGABRT stack-hook lifecycle, the elastic
mesh auto-sizing table, and the config <-> argv round trip.  The REAL
3-process SIGKILL/rejoin soak is tests/test_elastic_multiproc.py
(markers ``multiproc`` + ``slow``).
"""

import glob
import json
import os
import signal
import threading

import pytest

from scalable_agent_tpu.config import Config
from scalable_agent_tpu.obs import MetricsRegistry
from scalable_agent_tpu.parallel.mesh import auto_data_axis
from scalable_agent_tpu.runtime import elastic
from scalable_agent_tpu.runtime.elastic import (
    FATAL,
    LOST,
    OK,
    RESHARDABLE,
    RESTART_SAME,
    DriverLauncher,
    ElasticSupervisor,
    _exit_status,
    classify_exit,
    compatible_fleet_size,
    run_supervised,
)
from scalable_agent_tpu.runtime.exit_codes import (
    FLEET_EXIT_CODE,
    NONFINITE_EXIT_CODE,
    WATCHDOG_EXIT_CODE,
)
from scalable_agent_tpu.runtime.fleet import (
    EPOCH_VERDICT_NAME,
    FleetMonitor,
)


class VirtualClock:
    """clock()/sleep() pair where sleeping advances time instantly."""

    def __init__(self, start=1000.0):
        self.now = start

    def __call__(self):
        return self.now

    def sleep(self, seconds):
        self.now += seconds


class FakeWorker:
    """Scripted worker.  Behaviors:

    - ``("exit", code, delay_s)``: exits ``code`` once ``delay_s`` of
      virtual time passed since launch (terminate() is ignored — a
      worker already dying doesn't care).
    - ``("until_term", code)``: runs until terminate(), then exits
      ``code`` half a virtual second later (the grace-drain shape).

    ``side_effect=(fn, at_s)`` fires ``fn`` once when first polled
    ``at_s`` after launch — how tests grow the MTTR beacon file.
    """

    def __init__(self, clock, behavior, side_effect=None):
        self._clock = clock
        self._born = clock()
        self._behavior = behavior
        self._side_effect = side_effect
        self._fired = False
        self._terminated_at = None
        self.pid = 4242

    def poll(self):
        now = self._clock()
        if (self._side_effect and not self._fired
                and now - self._born >= self._side_effect[1]):
            self._fired = True
            self._side_effect[0]()
        kind = self._behavior[0]
        if kind == "exit":
            _, code, delay = self._behavior
            return code if now - self._born >= delay else None
        if kind == "until_term":
            if (self._terminated_at is not None
                    and now - self._terminated_at >= 0.5):
                return self._behavior[1]
            return None
        raise AssertionError(f"unknown behavior {self._behavior!r}")

    def terminate(self):
        if self._terminated_at is None:
            self._terminated_at = self._clock()


class FakeLauncher:
    """One scripted worker list per expected epoch; launching more
    epochs than scripted (or at the wrong size) fails the test."""

    def __init__(self, clock, scripts):
        self._clock = clock
        self._scripts = [list(s) for s in scripts]
        self.launches = []

    def launch(self, epoch, num_processes, port):
        assert self._scripts, (
            f"unexpected epoch {epoch} launch (script exhausted)")
        script = self._scripts.pop(0)
        assert len(script) == num_processes, (
            f"epoch {epoch}: script has {len(script)} workers, "
            f"supervisor launched {num_processes}")
        self.launches.append((epoch, num_processes, port))
        return [
            FakeWorker(self._clock, b[0] if isinstance(b, tuple)
                       and isinstance(b[0], tuple) else b,
                       side_effect=(b[1] if isinstance(b, tuple)
                                    and isinstance(b[0], tuple)
                                    else None))
            for b in script
        ]


def make_supervisor(tmp_path, clock, scripts, n=3, **kwargs):
    launcher = FakeLauncher(clock, scripts)
    kwargs.setdefault("restart_budget", 8)
    kwargs.setdefault("stable_s", 1e9)
    kwargs.setdefault("rejoin_delay_s", 1e9)
    kwargs.setdefault("backoff_initial_s", 1.0)
    kwargs.setdefault("backoff_cap_s", 8.0)
    supervisor = ElasticSupervisor(
        n, str(tmp_path), launcher,
        poll_s=0.5, clock=clock, sleep=clock.sleep,
        port_factory=lambda: 7777, registry=MetricsRegistry(),
        **kwargs)
    return supervisor, launcher


def epoch_events(tmp_path):
    path = os.path.join(str(tmp_path), elastic.EPOCHS_LOG_NAME)
    if not os.path.exists(path):
        return []
    return [json.loads(line)
            for line in open(path).read().splitlines() if line]


# ---------------------------------------------------------------------------
# Exit-code policy


class TestClassifyExit:
    def test_policy_table(self):
        assert classify_exit(0) == OK
        assert classify_exit(FLEET_EXIT_CODE) == RESHARDABLE
        assert classify_exit(NONFINITE_EXIT_CODE) == FATAL
        assert classify_exit(WATCHDOG_EXIT_CODE) == RESTART_SAME
        # SIGKILL = the host is gone; SIGABRT = jax's client fatal, a
        # SURVIVOR of someone else's death (runtime/fleet.py).
        assert classify_exit(-signal.SIGKILL) == LOST
        assert classify_exit(137) == LOST
        assert classify_exit(-signal.SIGABRT) == RESHARDABLE
        assert classify_exit(134) == RESHARDABLE
        # Garden-variety crash: restartable, host retained.
        assert classify_exit(1) == RESHARDABLE


# ---------------------------------------------------------------------------
# Supervisor state machine (scripted fleets, virtual clock)


class TestFleetSizeCompatibility:
    def test_largest_dividing_size_wins(self):
        # batch 256, 4 hosts, one lost: 3 doesn't divide -> run 2.
        assert compatible_fleet_size(256, 4) == 4
        assert compatible_fleet_size(256, 3) == 2
        assert compatible_fleet_size(6, 4) == 3
        assert compatible_fleet_size(7, 3) == 1  # prime batch: solo
        assert compatible_fleet_size(None, 5) == 5  # unconstrained

    def test_exit_status_translates_signals(self):
        assert _exit_status(-signal.SIGSEGV) == 139
        assert _exit_status(-signal.SIGTERM) == 143
        assert _exit_status(0) == 0
        assert _exit_status(FLEET_EXIT_CODE) == FLEET_EXIT_CODE


class TestSupervisorRun:
    def test_clean_completion_returns_zero_after_one_epoch(
            self, tmp_path):
        clock = VirtualClock()
        supervisor, launcher = make_supervisor(
            tmp_path, clock, [[("exit", 0, 1.0)] * 3])
        assert supervisor.run() == 0
        assert [(e, n) for e, n, _ in launcher.launches] == [(0, 3)]
        events = epoch_events(tmp_path)
        assert [e["event"] for e in events] == ["launch", "exit"]
        assert events[1]["outcome"] == "done"

    def test_sigkill_reshards_to_n_minus_1_then_completes(
            self, tmp_path):
        clock = VirtualClock()
        supervisor, launcher = make_supervisor(
            tmp_path, clock,
            [
                # Slot 1's host dies; the survivors exit 72 bounded.
                [("exit", FLEET_EXIT_CODE, 6.0), ("exit", -9, 1.0),
                 ("exit", FLEET_EXIT_CODE, 6.0)],
                [("exit", 0, 1.0)] * 2,
            ])
        assert supervisor.run() == 0
        assert [(e, n) for e, n, _ in launcher.launches] == [
            (0, 3), (1, 2)]
        events = epoch_events(tmp_path)
        exits = [e for e in events if e["event"] == "exit"]
        assert exits[0]["outcome"] == "reshard"
        assert exits[0]["lost_slots"] == [1]
        assert exits[1]["outcome"] == "done"
        # One membership-size change counted.
        assert supervisor._resizes.value == 1
        assert supervisor.available_slots() == [0, 2]

    def test_reshard_skips_batch_incompatible_size(self, tmp_path):
        """batch 256 over 4 hosts: losing one cannot relaunch as 3
        (256 % 3 != 0) — the supervisor runs 2 and idles the third
        slot instead of dying at launch."""
        clock = VirtualClock()
        supervisor, launcher = make_supervisor(
            tmp_path, clock,
            [
                [("exit", FLEET_EXIT_CODE, 6.0), ("exit", -9, 1.0),
                 ("exit", FLEET_EXIT_CODE, 6.0),
                 ("exit", FLEET_EXIT_CODE, 6.0)],
                [("exit", 0, 1.0)] * 2,
            ],
            n=4, batch_size=256)
        assert supervisor.run() == 0
        assert [(e, n) for e, n, _ in launcher.launches] == [
            (0, 4), (1, 2)]
        launch1 = [e for e in epoch_events(tmp_path)
                   if e["event"] == "launch"][1]
        # The first two surviving slots run; slot 3 idles this epoch.
        assert launch1["slots"] == [0, 2]

    def test_persistent_segfaults_exit_posix_status(self, tmp_path):
        """A fleet that keeps dying -11 must exhaust the budget with
        the POSIX 139, not a raw negative Popen code (the OS would
        render -11 as a meaningless 245)."""
        clock = VirtualClock()
        supervisor, _ = make_supervisor(
            tmp_path, clock,
            [[("exit", -signal.SIGSEGV, 0.5)]] * 2,
            n=1, restart_budget=1)
        assert supervisor.run() == 139

    def test_rejoin_scales_back_up_at_checkpoint_boundary(
            self, tmp_path):
        clock = VirtualClock()
        beacon = os.path.join(str(tmp_path), "metrics.jsonl")

        def grow_beacon():
            with open(beacon, "a") as f:
                f.write('{"update": 1}\n')

        supervisor, launcher = make_supervisor(
            tmp_path, clock,
            [
                [("exit", FLEET_EXIT_CODE, 6.0), ("exit", -9, 1.0),
                 ("exit", FLEET_EXIT_CODE, 6.0)],
                # The resharded fleet trains (grows the beacon) until
                # the supervisor drains it for the scale-up.
                [(("until_term", 0), (grow_beacon, 2.0)),
                 ("until_term", 0)],
                [("exit", 0, 1.0)] * 3,
            ],
            rejoin_delay_s=30.0)
        assert supervisor.run() == 0
        assert [(e, n) for e, n, _ in launcher.launches] == [
            (0, 3), (1, 2), (2, 3)]
        events = epoch_events(tmp_path)
        outcomes = [e["outcome"] for e in events
                    if e["event"] == "exit"]
        assert outcomes == ["reshard", "scale_up", "done"]
        assert any(e["event"] == "scale_up_drain" for e in events)
        # Down to 2 then back to 3: two membership-size changes.
        assert supervisor._resizes.value == 2
        assert supervisor.available_slots() == [0, 1, 2]
        # MTTR: first observed death (epoch 0) -> beacon growth
        # (epoch 1), measured on the virtual clock.
        mttrs = [e for e in events if e["event"] == "mttr"]
        assert len(mttrs) == 1
        assert 0.0 < mttrs[0]["mttr_s"] < 60.0
        assert supervisor._last_mttr_s == pytest.approx(
            mttrs[0]["mttr_s"], abs=1e-6)

    def test_rejoin_marker_file_forces_early_rejoin(self, tmp_path):
        clock = VirtualClock()
        (tmp_path / "rejoin.1").write_text("back")
        supervisor, launcher = make_supervisor(
            tmp_path, clock,
            [
                [("exit", FLEET_EXIT_CODE, 6.0), ("exit", -9, 1.0),
                 ("exit", FLEET_EXIT_CODE, 6.0)],
                [("until_term", 0)] * 2,
                [("exit", 0, 1.0)] * 3,
            ],
            rejoin_delay_s=1e9)  # only the marker can trigger it
        assert supervisor.run() == 0
        assert [n for _, n, _ in launcher.launches] == [3, 2, 3]
        # The consumed marker is deleted at rejoin.
        assert not (tmp_path / "rejoin.1").exists()

    def test_preempt_verdict_relaunches_instead_of_finishing(
            self, tmp_path):
        clock = VirtualClock()

        # A drained preemption exits 0 everywhere — only the
        # epoch-stamped verdict (written by the FLEET mid-epoch, like
        # the real monitor does) tells the supervisor to relaunch.
        def write_preempt_verdict():
            (tmp_path / EPOCH_VERDICT_NAME).write_text(json.dumps(
                {"epoch": 0, "kind": "preempt"}))

        supervisor, launcher = make_supervisor(
            tmp_path, clock,
            [[(("exit", 0, 1.0), (write_preempt_verdict, 0.5))],
             [("exit", 0, 1.0)]], n=1)
        assert supervisor.run() == 0
        # Epoch 0's clean exit re-read as a preemption; epoch 1's
        # clean exit finds the verdict CLEARED at its launch -> done.
        assert [e for e, _, _ in launcher.launches] == [0, 1]

    def test_stale_incarnation_verdict_cleared_at_launch(
            self, tmp_path):
        """A fleet_epoch.json left by a PREVIOUS supervisor
        incarnation (epoch numbering restarts at 0, so the epoch-match
        check alone would accept it) must not re-read a finished run
        as a preemption."""
        clock = VirtualClock()
        (tmp_path / EPOCH_VERDICT_NAME).write_text(json.dumps(
            {"epoch": 0, "kind": "preempt"}))
        supervisor, launcher = make_supervisor(
            tmp_path, clock, [[("exit", 0, 1.0)]], n=1)
        assert supervisor.run() == 0
        assert len(launcher.launches) == 1  # done, no phantom relaunch

    def test_fatal_nonfinite_stops_the_supervisor(self, tmp_path):
        clock = VirtualClock()
        supervisor, _ = make_supervisor(
            tmp_path, clock,
            [[("exit", NONFINITE_EXIT_CODE, 1.0)]], n=1)
        assert supervisor.run() == NONFINITE_EXIT_CODE

    def test_restart_budget_exhausts_with_backoff(self, tmp_path):
        clock = VirtualClock()
        supervisor, launcher = make_supervisor(
            tmp_path, clock,
            [[("exit", 1, 0.5)], [("exit", 1, 0.5)]],
            n=1, restart_budget=1)
        assert supervisor.run() == 1
        assert len(launcher.launches) == 2
        assert any(e["event"] == "budget_exhausted"
                   for e in epoch_events(tmp_path))

    def test_stable_epoch_resets_the_budget(self, tmp_path):
        clock = VirtualClock()
        # budget=1: two UNRESET consecutive failures would exhaust it.
        # Epoch 1 runs past stable_s before failing, so its failure
        # charges from a reset counter and the fleet relaunches.
        supervisor, launcher = make_supervisor(
            tmp_path, clock,
            [[("exit", 1, 0.5)],       # failure 1/1
             [("exit", 1, 20.0)],      # stable: reset, then 1/1
             [("exit", 0, 0.5)]],
            n=1, restart_budget=1, stable_s=10.0)
        assert supervisor.run() == 0
        assert len(launcher.launches) == 3

    def test_shutdown_request_drains_and_exits_zero(self, tmp_path):
        clock = VirtualClock()
        box = {}

        def request_shutdown():
            box["supervisor"]._shutdown_requested = True

        supervisor, launcher = make_supervisor(
            tmp_path, clock,
            [[(("until_term", 0), (request_shutdown, 2.0)),
              ("until_term", 0), ("until_term", 0)]])
        box["supervisor"] = supervisor
        assert supervisor.run() == 0
        events = epoch_events(tmp_path)
        assert events[-1]["outcome"] == "shutdown"

    def test_shutdown_between_epochs_launches_nothing(self, tmp_path):
        clock = VirtualClock()
        supervisor, launcher = make_supervisor(tmp_path, clock, [])
        supervisor._shutdown_requested = True
        assert supervisor.run() == 0
        assert launcher.launches == []

    def test_backoff_is_capped_exponential(self, tmp_path):
        clock = VirtualClock()
        supervisor, _ = make_supervisor(
            tmp_path, clock, [], backoff_initial_s=1.0,
            backoff_cap_s=8.0)
        assert supervisor.backoff_s() == 0.0
        observed = []
        for failures in range(1, 7):
            supervisor._consecutive_failures = failures
            observed.append(supervisor.backoff_s())
        assert observed == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]


# ---------------------------------------------------------------------------
# Launcher command construction + run_supervised validation


class TestDriverLauncher:
    def test_worker_command_carries_epoch_and_distributed_flags(
            self, monkeypatch):
        calls = []

        class FakePopen:
            def __init__(self, args, env=None):
                calls.append((args, env))
                self.pid = 1

        monkeypatch.setattr(elastic.subprocess, "Popen", FakePopen)
        config = Config(batch_size=6, elastic=True, fleet_epoch=9,
                        distributed_num_processes=3,
                        logdir="/tmp/elastic_x")
        workers = DriverLauncher(config).launch(
            epoch=2, num_processes=2, port=777)
        assert len(workers) == 2
        args0, args1 = calls[0][0], calls[1][0]
        assert "--fleet_epoch=2" in args0
        assert "--distributed_coordinator=localhost:777" in args0
        assert "--distributed_num_processes=2" in args0
        assert "--distributed_process_id=0" in args0
        assert "--distributed_process_id=1" in args1
        assert "--batch_size=6" in args0
        # Supervisor-owned fields must not leak into workers — a
        # worker relaunching the supervisor would fork-bomb.
        assert not any(a.startswith("--elastic=") for a in args0)

    def test_run_supervised_rejects_indivisible_batch(self):
        config = Config(batch_size=5, elastic=True,
                        distributed_num_processes=2)
        with pytest.raises(ValueError, match="not divisible"):
            run_supervised(config)

    def test_config_argv_round_trip(self):
        config = Config(batch_size=6, elastic=True, fleet_epoch=4,
                        peer_timeout_s=7.5, level_name="fake_small")
        rebuilt = Config.from_argv(config.to_argv())
        assert rebuilt == config
        # to_argv(exclude=...) drops the named fields back to default.
        stripped = Config.from_argv(
            config.to_argv(exclude=("elastic", "fleet_epoch")))
        assert not stripped.elastic
        assert stripped.fleet_epoch == 0
        assert stripped.batch_size == 6


# ---------------------------------------------------------------------------
# Elastic mesh auto-sizing (parallel/mesh.py)


class TestAutoDataAxis:
    def test_adapts_across_device_counts(self):
        # One global batch of 32 resharding over whatever devices the
        # membership epoch has — the elastic invariant.
        assert auto_data_axis(32, 8) == 8
        assert auto_data_axis(32, 6) == 2
        assert auto_data_axis(32, 4) == 4
        assert auto_data_axis(32, 1) == 1
        # Batch smaller than the host: use a divisor, don't fail.
        assert auto_data_axis(4, 8) == 4
        assert auto_data_axis(6, 8) == 2
        # seq/model take their devices first.
        assert auto_data_axis(32, 8, seq=2) == 4
        assert auto_data_axis(32, 8, model=2) == 4
        assert auto_data_axis(32, 8, seq=2, model=2) == 2

    def test_matches_driver_resolution(self, monkeypatch):
        import jax

        from scalable_agent_tpu.driver import resolve_mesh_data

        config = Config(batch_size=32, mesh_data=0)
        assert resolve_mesh_data(config) == auto_data_axis(
            32, len(jax.devices()))


# ---------------------------------------------------------------------------
# Fleet monitor: membership verdicts + kv_suspect early dump


class FakeKV:
    def __init__(self):
        self.store = {}
        self.fail_with = None

    def _maybe_fail(self):
        if self.fail_with is not None:
            raise self.fail_with

    def key_value_set(self, key, value, allow_overwrite=False):
        self._maybe_fail()
        self.store[key] = value

    def key_value_dir_get(self, prefix):
        self._maybe_fail()
        return [(k, v) for k, v in self.store.items()
                if k.startswith(prefix)]


class Clock:
    def __init__(self, start=100.0):
        self.now = start

    def __call__(self):
        return self.now


class RecorderStub:
    def __init__(self):
        self.events = []
        self.dumps = []
        self.reason_pin = None
        self.dumped = threading.Event()

    def record(self, kind, name, args=None):
        self.events.append((kind, name, args))

    def dump_all(self, reason, **kwargs):
        self.dumps.append(reason)
        self.dumped.set()


def make_monitor(tmp_path, clock, kv, epoch=0, recorder=None,
                 timeout=5.0):
    fatals = []
    monitor = FleetMonitor(
        peer_timeout_s=timeout, preemption_grace_s=0.0,
        registry=MetricsRegistry(), process_index=0, num_processes=2,
        kv=kv, clock=clock, on_fatal=fatals.append,
        host_exit_linger_s=0.0, epoch=epoch,
        logdir=str(tmp_path),
        recorder=recorder or RecorderStub())
    monitor._test_fatals = fatals
    return monitor


class TestMembershipVerdict:
    def test_peer_lost_fatal_writes_epoch_verdict(self, tmp_path):
        clock, kv = Clock(), FakeKV()
        monitor = make_monitor(tmp_path, clock, kv, epoch=3)
        kv.store["fleet/hb/1"] = "1"
        monitor.publish_once()
        monitor.monitor_once()
        monitor.note_checkpoint(7)
        monitor.note_checkpoint(5)  # older step never regresses it
        clock.now += 6.0
        monitor.publish_once()  # own plane fresh: verdict may land
        monitor.monitor_once()
        assert monitor._test_fatals == [FLEET_EXIT_CODE]
        verdict = json.load(
            open(os.path.join(str(tmp_path), EPOCH_VERDICT_NAME)))
        assert verdict["epoch"] == 3
        assert verdict["kind"] == "peer_lost"
        assert verdict["lost_peers"] == [1]
        assert verdict["last_verified_step"] == 7
        assert verdict["num_processes"] == 2

    def test_preempt_decision_writes_epoch_verdict(self, tmp_path):
        clock, kv = Clock(), FakeKV()
        monitor = make_monitor(tmp_path, clock, kv, epoch=1)
        monitor._grace.grace_s = 30.0
        monitor.note_checkpoint(4)
        monitor.note_preempt_decision(12)
        verdict = json.load(
            open(os.path.join(str(tmp_path), EPOCH_VERDICT_NAME)))
        assert verdict["kind"] == "preempt"
        assert verdict["epoch"] == 1
        assert verdict["detail"]["update"] == 12
        assert verdict["last_verified_step"] == 4

    def test_unwinding_exception_writes_collective_error_verdict(
            self, tmp_path):
        """The driver's finally lands a verdict when an exception is
        unwinding a multi-process run — the aborted collective's
        XlaRuntimeError (then jax's own SIGABRT) can otherwise end the
        process before the monitor's heartbeat verdict exists."""
        clock, kv = Clock(), FakeKV()
        monitor = make_monitor(tmp_path, clock, kv, epoch=2)
        monitor.note_checkpoint(9)
        monitor.note_fatal_error(RuntimeError("gloo all-reduce failed"))
        verdict = json.load(
            open(os.path.join(str(tmp_path), EPOCH_VERDICT_NAME)))
        assert verdict["kind"] == "collective_error"
        assert verdict["epoch"] == 2
        assert verdict["last_verified_step"] == 9
        assert verdict["detail"]["error_type"] == "RuntimeError"

    def test_monitor_verdict_keeps_precedence_over_exception(
            self, tmp_path):
        """Once the monitor's own fatal fired (richer: names the stale
        peer), a late note_fatal_error must not clobber it."""
        clock, kv = Clock(), FakeKV()
        monitor = make_monitor(tmp_path, clock, kv, epoch=4)
        kv.store["fleet/hb/1"] = "1"
        monitor.publish_once()
        monitor.monitor_once()
        clock.now += 6.0
        monitor.publish_once()
        monitor.monitor_once()
        assert monitor._test_fatals == [FLEET_EXIT_CODE]
        monitor.note_fatal_error(RuntimeError("late unwind"))
        verdict = json.load(
            open(os.path.join(str(tmp_path), EPOCH_VERDICT_NAME)))
        assert verdict["kind"] == "peer_lost"

    def test_note_fatal_error_noop_single_process(self, tmp_path):
        monitor = FleetMonitor(
            peer_timeout_s=5.0, preemption_grace_s=30.0,
            registry=MetricsRegistry(), process_index=0,
            num_processes=1, kv=None, clock=Clock(),
            on_fatal=lambda code: None, host_exit_linger_s=0.0,
            logdir=str(tmp_path), recorder=RecorderStub())
        monitor.note_fatal_error(RuntimeError("local bug"))
        assert not os.path.exists(
            os.path.join(str(tmp_path), EPOCH_VERDICT_NAME))

    def test_no_logdir_writes_nothing(self, tmp_path):
        clock, kv = Clock(), FakeKV()
        monitor = FleetMonitor(
            peer_timeout_s=5.0, registry=MetricsRegistry(),
            process_index=0, num_processes=2, kv=kv, clock=clock,
            on_fatal=lambda code: None, host_exit_linger_s=0.0,
            recorder=RecorderStub())
        monitor._write_epoch_verdict("peer_lost", {})
        assert not glob.glob(os.path.join(str(tmp_path), "*.json"))

    def test_epoch_gauge_registered(self, tmp_path):
        registry = MetricsRegistry()
        FleetMonitor(
            peer_timeout_s=5.0, registry=registry, process_index=0,
            num_processes=2, kv=FakeKV(), clock=Clock(),
            on_fatal=lambda code: None, host_exit_linger_s=0.0,
            epoch=5, logdir=str(tmp_path), recorder=RecorderStub())
        assert registry.gauge("fleet/epoch").value == 5.0


class TestKvSuspectEarlyDump:
    def test_first_kv_failure_fires_one_early_dump(self, tmp_path):
        clock, kv = Clock(), FakeKV()
        recorder = RecorderStub()
        monitor = make_monitor(tmp_path, clock, kv, recorder=recorder,
                               timeout=60.0)
        kv.fail_with = RuntimeError("connection refused")
        monitor.monitor_once()
        assert recorder.dumped.wait(timeout=5.0)
        assert recorder.dumps == ["fleet:kv_suspect"]
        assert any(kind == "fleet_suspect"
                   for kind, _, _ in recorder.events)
        # Later failing polls must NOT re-dump (once per run).
        clock.now += 1.0
        monitor.monitor_once()
        assert recorder.dumps == ["fleet:kv_suspect"]
        # No fatal yet: the deadline still owns the verdict.
        assert monitor._test_fatals == []


# ---------------------------------------------------------------------------
# SIGABRT stack-hook lifecycle (obs/flightrec.py)


class TestSigabrtHook:
    """The hook must be proven in SUBPROCESSES: pytest's own
    faulthandler plugin keeps the in-process handler enabled (which
    the hook correctly refuses to hijack), and a real ``os.abort()``
    would kill the test runner."""

    HEADER = (
        "import glob, os, sys\n"
        "sys.path.insert(0, {repo!r})\n"
        "from scalable_agent_tpu.obs.flightrec import (\n"
        "    FlightRecorder, install_crash_handlers)\n"
        "rec = FlightRecorder(logdir={logdir!r})\n"
        "uninstall = install_crash_handlers(rec)\n"
        "paths = glob.glob(os.path.join({logdir!r}, "
        "'stacks.sigabrt.*.txt'))\n"
        "assert len(paths) == 1, paths\n"
    )

    @staticmethod
    def _run(body, logdir):
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        script = TestSigabrtHook.HEADER.format(
            repo=repo, logdir=str(logdir)) + body
        return subprocess.run(
            [sys.executable, "-c", script], capture_output=True,
            text=True, timeout=60)

    def test_clean_uninstall_leaves_no_litter(self, tmp_path):
        proc = self._run(
            "uninstall()\n"
            "assert not glob.glob(os.path.join({logdir!r}, "
            "'stacks.sigabrt.*.txt'))\n".format(logdir=str(tmp_path)),
            tmp_path)
        assert proc.returncode == 0, proc.stderr[-2000:]

    def test_real_abort_lands_thread_stacks(self, tmp_path):
        # The jax-client-fatal shape: abort() from a live process.
        # The C-level faulthandler must land every thread's stack in
        # the pre-opened file as the process dies with signal 6.
        proc = self._run("os.abort()\n", tmp_path)
        assert proc.returncode == -signal.SIGABRT, proc.stderr[-2000:]
        paths = glob.glob(
            os.path.join(str(tmp_path), "stacks.sigabrt.*.txt"))
        assert len(paths) == 1
        content = open(paths[0]).read()
        assert "Aborted" in content and "thread" in content, (
            content[:500])


# ---------------------------------------------------------------------------
# Aggregation: membership series fold rules + supervisor snapshot


class TestAggregationFolds:
    def test_epoch_and_mttr_fold_max(self):
        from scalable_agent_tpu.obs.aggregate import (
            aggregate_prometheus,
        )

        texts = {
            "0": ("# TYPE impala_fleet_epoch gauge\n"
                  "impala_fleet_epoch 3.0\n"
                  "# TYPE impala_fleet_mttr_s gauge\n"
                  "impala_fleet_mttr_s 12.5\n"),
            "1": ("# TYPE impala_fleet_epoch gauge\n"
                  "impala_fleet_epoch 2.0\n"
                  "# TYPE impala_fleet_mttr_s gauge\n"
                  "impala_fleet_mttr_s 40.0\n"),
        }
        out = aggregate_prometheus(texts)
        assert 'impala_fleet_epoch{fold="max"} 3.0' in out
        assert 'impala_fleet_mttr_s{fold="max"} 40.0' in out

    def test_supervisor_prom_gets_its_own_label(self, tmp_path):
        from scalable_agent_tpu.obs.aggregate import find_artifacts

        (tmp_path / "metrics.prom").write_text("")
        (tmp_path / "metrics.p1.prom").write_text("")
        (tmp_path / "metrics.supervisor.prom").write_text("")
        _, proms = find_artifacts(str(tmp_path))
        assert set(proms) == {"0", "1", "supervisor"}


# ---------------------------------------------------------------------------
# Supervisor steady-state cycle (the bench-timed surface)


class TestWatchCycle:
    def test_cycle_reports_codes_and_mttr(self, tmp_path):
        clock = VirtualClock()
        supervisor, _ = make_supervisor(tmp_path, clock, [])
        workers = [FakeWorker(clock, ("exit", 0, 5.0))
                   for _ in range(3)]
        codes, mttr = supervisor.watch_cycle(workers, 0, None)
        assert codes == [None, None, None]
        assert mttr is None
        # Beacon growth with an anchor -> MTTR measured.
        beacon = tmp_path / "metrics.jsonl"
        beacon.write_text('{"update": 1}\n')
        clock.now += 7.0
        codes, mttr = supervisor.watch_cycle(
            workers, 0, clock.now - 3.0)
        assert codes == [0, 0, 0]
        assert mttr == pytest.approx(3.0)
