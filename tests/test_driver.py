"""Driver end-to-end smoke tests (the reference's only end-to-end coverage
is a Docker run: train 10k frames then test 5 episodes, Dockerfile:78 —
here it is an actual hermetic test on the FakeEnv)."""

import glob
import json
import os

import numpy as np
import pytest

from scalable_agent_tpu.config import Config, apply_env_overrides
from scalable_agent_tpu.driver import test as run_test
from scalable_agent_tpu.driver import train as run_train


def small_config(tmp_path, **overrides) -> Config:
    defaults = dict(
        mode="train",
        logdir=str(tmp_path / "run"),
        level_name="fake_small",
        num_actors=8,
        batch_size=4,
        unroll_length=5,
        num_action_repeats=4,
        total_environment_frames=240,  # 3 updates of 80 frames
        height=16,
        width=16,
        num_env_workers_per_group=2,
        test_num_episodes=2,
        compute_dtype="float32",
        checkpoint_interval_s=0.0,  # save every update
        log_interval_s=0.001,
        seed=3,
    )
    defaults.update(overrides)
    return Config(**defaults)


@pytest.mark.slow
class TestDriver:
    def test_train_then_test_roundtrip(self, tmp_path):
        config = small_config(tmp_path)
        metrics = run_train(config)
        assert metrics["env_frames"] == 240
        assert np.isfinite(metrics["total_loss"])
        # config.json persisted.
        saved = json.load(open(os.path.join(config.logdir, "config.json")))
        assert saved["level_name"] == "fake_small"
        # metrics.jsonl has rows with reference metric names.
        rows = [json.loads(line) for line in
                open(os.path.join(config.logdir, "metrics.jsonl"))]
        assert any("total_loss" in r for r in rows)
        assert any("learning_rate" in r for r in rows)
        # checkpoint written.
        assert glob.glob(os.path.join(config.logdir, "checkpoints", "*"))

        # Resume: train 80 more frames from the checkpoint.
        config2 = small_config(tmp_path, total_environment_frames=320)
        metrics2 = run_train(config2)
        assert metrics2["env_frames"] == 320

        # Test mode restores and evaluates.
        test_config = small_config(tmp_path, mode="test")
        level_returns = run_test(test_config)
        returns = level_returns["fake_small"]
        assert len(returns) == 2
        # fake_small episodes: 10 steps of 0.1*(t%3) + terminal 1.0.
        expected = sum(0.1 * (t % 3) for t in range(1, 11)) + 1.0
        np.testing.assert_allclose(returns, expected, rtol=1e-5)


@pytest.mark.slow
class TestRealSimulator:
    def test_train_on_gymnasium_cartpole(self, tmp_path):
        """End-to-end train on a REAL simulator (gymnasium CartPole with
        rendered frames) — the reference can only do this with
        VizDoom/DMLab installed; the gym_ family makes it hermetic."""
        config = small_config(
            tmp_path, level_name="gym_CartPole-v1", num_actors=4,
            num_action_repeats=2,
            total_environment_frames=80)  # 2 updates of 40 frames
        try:
            metrics = run_train(config)
        except Exception as exc:
            message = str(exc).lower()
            if "render" in message or "not available" in message:
                pytest.skip(f"gymnasium unavailable: {exc}")
            raise
        assert metrics["env_frames"] == 80
        assert np.isfinite(metrics["total_loss"])


@pytest.mark.slow
class TestSingleDeviceMesh:
    def test_train_on_one_device_mesh(self, tmp_path):
        """Regression: with a 1-device mesh the actors' weight snapshot
        lives on the learner's own device; the learner's donated update
        must not invalidate it (ActorPool.set_params forces a copy)."""
        config = small_config(
            tmp_path, mesh_data=1, num_actors=4,
            total_environment_frames=160)  # 2 updates of 80 frames
        metrics = run_train(config)
        assert metrics["env_frames"] == 160
        assert np.isfinite(metrics["total_loss"])


class TestConfig:
    def test_env_overrides(self):
        config = Config(level_name="atari_breakout")
        out = apply_env_overrides(config)
        assert (out.width, out.height) == (84, 84)
        # Explicit user value wins.
        config = Config(level_name="atari_breakout", width=100)
        assert apply_env_overrides(config).width == 100

    def test_json_roundtrip(self, tmp_path):
        config = Config(logdir=str(tmp_path), batch_size=7)
        path = config.save()
        loaded = Config.load(path)
        assert loaded == config

    def test_from_checkpoint_dir_overrides(self, tmp_path):
        Config(logdir=str(tmp_path), batch_size=7).save()
        loaded = Config.from_checkpoint_dir(str(tmp_path), seed=9)
        assert loaded.batch_size == 7 and loaded.seed == 9

    def test_frames_per_update(self):
        config = Config(batch_size=32, unroll_length=100,
                        num_action_repeats=4)
        assert config.frames_per_update() == 12800


@pytest.mark.slow
class TestCoreImplCheckpointInterop:
    def test_resume_across_core_impls(self, tmp_path):
        """Checkpoints are interchangeable between core_impl='xla' and
        'pallas' (identical param trees — models/agent.py): train with
        one, resume with the other, frames and LR schedule continue."""
        config = small_config(tmp_path, core_impl="xla")
        metrics = run_train(config)
        assert metrics["env_frames"] == 240

        rows_before = sum(
            1 for line in open(os.path.join(config.logdir, "metrics.jsonl"))
            if "total_loss" in line)

        config2 = small_config(tmp_path, total_environment_frames=320,
                               core_impl="pallas")
        metrics2 = run_train(config2)
        assert metrics2["env_frames"] == 320
        assert np.isfinite(metrics2["total_loss"])
        # The resumed run really CONTINUED from frame 240: exactly one
        # more 80-frame update was trained (a silent from-scratch
        # retrain would log 320/80 = 4 new update rows).
        rows_after = sum(
            1 for line in open(os.path.join(config.logdir, "metrics.jsonl"))
            if "total_loss" in line)
        assert rows_after - rows_before == 1, (rows_before, rows_after)


@pytest.mark.slow
class TestEvalRecording:
    def test_record_to_writes_episodes(self, tmp_path):
        """--record_to in test mode writes frames.npy + episode.json
        per completed episode, one dir per env slot (the SF record_to
        flag's role, reference env_wrappers.py:433-497)."""
        config = small_config(tmp_path)
        run_train(config)
        record_dir = str(tmp_path / "recordings")
        test_config = small_config(tmp_path, mode="test",
                                   record_to=record_dir,
                                   test_num_episodes=2)
        returns = run_test(test_config)
        assert len(returns["fake_small"]) == 2
        episodes = glob.glob(os.path.join(
            record_dir, "fake_small", "env_*", "episode_*"))
        assert episodes, record_dir
        frames = np.load(os.path.join(episodes[0], "frames.npy"))
        assert frames.ndim == 4 and frames.dtype == np.uint8
        meta = json.load(open(os.path.join(episodes[0], "episode.json")))
        # frames = initial + one per action.
        assert len(meta["actions"]) == len(meta["rewards"])
        assert frames.shape[0] == len(meta["actions"]) + 1


@pytest.mark.slow
class TestInGraphBackend:
    """--train_backend=ingraph: the fused rollout+update program as a
    CLI-reachable training mode with checkpoint/metrics/resume parity
    (VERDICT r3 item 5; replaces the reference's host actor pipeline,
    experiment.py:479-672, for device-expressible levels)."""

    def test_ingraph_trains_checkpoints_resumes(self, tmp_path):
        config = small_config(
            tmp_path, train_backend="ingraph", level_name="fake_benchmark",
            num_actors=4, batch_size=4, unroll_length=5,
            num_action_repeats=4,
            total_environment_frames=240)  # 3 updates of 80 frames
        metrics = run_train(config)
        assert metrics["env_frames"] == 240
        assert np.isfinite(metrics["total_loss"])
        rows = [json.loads(line) for line in
                open(os.path.join(config.logdir, "metrics.jsonl"))]
        assert any("total_loss" in r for r in rows)
        assert any("learning_rate" in r for r in rows)
        assert glob.glob(os.path.join(config.logdir, "checkpoints", "*"))

        # Resume continues the frame count (and LR schedule) exactly.
        config2 = small_config(
            tmp_path, train_backend="ingraph", level_name="fake_benchmark",
            num_actors=4, batch_size=4, unroll_length=5,
            num_action_repeats=4, total_environment_frames=320)
        metrics2 = run_train(config2)
        assert metrics2["env_frames"] == 320
        rows_after = sum(
            1 for line in open(os.path.join(config.logdir, "metrics.jsonl"))
            if "total_loss" in line)
        # One more 80-frame update, not a from-scratch retrain.
        assert rows_after - len(rows) == 1

    def test_ingraph_reports_episode_metrics(self, tmp_path):
        """The fused path logs device-computed episode stats (metrics
        parity with the host backend's ring-buffer means)."""
        config = small_config(
            tmp_path, train_backend="ingraph", level_name="fake_small",
            num_actors=4, batch_size=4, unroll_length=5,
            num_action_repeats=2,
            # 6 updates of 40 frames; fake_small episodes last 10
            # agent steps, so episodes finish from update 2 on.
            total_environment_frames=240,
            checkpoint_interval_s=1e9)
        run_train(config)
        rows = [json.loads(line) for line in
                open(os.path.join(config.logdir, "metrics.jsonl"))]
        with_stats = [r for r in rows if "episode_return" in r]
        assert with_stats
        # fake_small: 10 steps of 0.1*(t%3) + terminal 1.0.
        expected = sum(0.1 * (t % 3) for t in range(1, 11)) + 1.0
        np.testing.assert_allclose(
            with_stats[-1]["episode_return"], expected, rtol=1e-4)
        # episode_frames = agent steps x action repeats = the episode's
        # 10 SIMULATOR steps (native repeats: 5 agent steps x 2).
        assert with_stats[-1]["episode_frames"] == pytest.approx(10)
        assert all("episodes_completed" not in r for r in rows)

    def test_ingraph_rejects_host_only_levels(self, tmp_path):
        config = small_config(tmp_path, train_backend="ingraph",
                              level_name="fake_tuple")
        with pytest.raises(ValueError, match="in-graph"):
            run_train(config)


@pytest.mark.slow
class TestMultiTaskTraining:
    """--mode=train --level_name=dmlab30 spreads env slots over all 30
    train levels with per-level metrics and a training suite score
    (reference: experiment.py:552-555, 634-667, 711-717)."""

    @pytest.fixture(autouse=True)
    def fake_lab(self):
        import sys
        fakes = os.path.join(os.path.dirname(__file__), "fakes")
        sys.path.insert(0, fakes)
        sys.modules.pop("deepmind_lab", None)
        yield
        sys.path.remove(fakes)
        sys.modules.pop("deepmind_lab", None)

    def test_dmlab30_train_emits_per_level_and_suite_scores(self, tmp_path):
        from scalable_agent_tpu.driver import training_level_names
        from scalable_agent_tpu.envs import dmlab30

        config = small_config(
            tmp_path,
            level_name="dmlab30",
            num_actors=30,
            batch_size=30,
            unroll_length=6,
            num_action_repeats=2,
            num_env_workers_per_group=3,
            height=24, width=32,
            # 4 updates of 30*6*2 = 360 frames.
            total_environment_frames=4 * 360,
            checkpoint_interval_s=1e9,
        )
        resolved = apply_env_overrides(config)
        assert resolved.use_instruction  # language levels need INSTR
        levels = training_level_names(resolved)
        assert len(levels) == 30
        assert levels[0] == f"dmlab_{dmlab30.TRAIN_LEVELS[0]}"

        metrics = run_train(config)
        assert np.isfinite(metrics["total_loss"])

        rows = [json.loads(line) for line in
                open(os.path.join(config.logdir, "metrics.jsonl"))]
        per_level = {k for r in rows for k in r
                     if k.startswith("dmlab_")
                     and k.endswith("/episode_return")}
        # >= 2 distinct levels contributed episode stats.
        assert len(per_level) >= 2, per_level
        # Matching frame metrics carry the action-repeat factor.
        frames_keys = {k for r in rows for k in r
                       if k.endswith("/episode_frames")}
        assert frames_keys
        # The capped/uncapped human-normalized TRAINING score was
        # emitted at least once.
        assert any("dmlab30/training_cap_100" in r for r in rows)
        assert any("dmlab30/training_no_cap" in r for r in rows)


@pytest.mark.slow
class TestCliSubprocess:
    def test_main_module_trains(self, tmp_path):
        """The exact user-facing command (`python -m
        scalable_agent_tpu.driver --...`) runs a short hermetic train —
        covering main()'s argparse bridge, not just train()."""
        import subprocess
        import sys

        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=os.pathsep.join(
                [os.path.dirname(os.path.dirname(os.path.abspath(
                    __file__)))] + os.environ.get(
                        "PYTHONPATH", "").split(os.pathsep)),
        )
        logdir = tmp_path / "cli_run"
        result = subprocess.run(
            [sys.executable, "-m", "scalable_agent_tpu.driver",
             "--mode=train", f"--logdir={logdir}",
             "--level_name=fake_small", "--num_actors=4",
             "--batch_size=2", "--unroll_length=4",
             "--num_action_repeats=1", "--height=16", "--width=16",
             "--total_environment_frames=16",
             "--compute_dtype=float32", "--checkpoint_interval_s=1e9"],
            env=env, capture_output=True, text=True, timeout=420)
        assert result.returncode == 0, result.stderr[-2000:]
        assert (logdir / "config.json").exists()
        assert (logdir / "metrics.jsonl").exists()
