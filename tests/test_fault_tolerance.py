"""Env-worker fault tolerance: a dead simulator process must not kill
training (SURVEY §5.3; VERDICT r2 item 6).

Fault injection: SIGKILL a MultiEnv worker mid-run and assert the batch
keeps stepping (the dead slice restarts as fresh episodes with shifted
seeds), episode stats stay unbroken, and the full ActorPool -> Learner
loop trains through the kill.
"""

import functools
import time

import jax
import numpy as np
import pytest

from scalable_agent_tpu.envs import MultiEnv, make_impala_stream
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.envs.worker import RemoteEnvError
from scalable_agent_tpu.models import ImpalaAgent
from scalable_agent_tpu.models import agent as agent_mod
from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.runtime import (
    ActorPool,
    Learner,
    LearnerHyperparams,
    Trajectory,
)

NUM_ACTIONS = 4
FRAME = TensorSpec((8, 8, 3), np.uint8, "frame")


def make_envs(n=4, workers=2, episode_length=3, **kwargs):
    fns = [functools.partial(
        make_impala_stream, "fake_small", seed=i, height=8, width=8,
        num_actions=NUM_ACTIONS, episode_length=episode_length)
        for i in range(n)]
    return MultiEnv(fns, FRAME, num_workers=workers, **kwargs)


class TestWorkerRespawn:
    def test_kill_mid_run_recovers_with_fresh_episodes(self):
        envs = make_envs()
        try:
            envs.initial()
            for _ in range(2):
                out = envs.step(np.zeros((4,), np.int64))
            old_pid = envs._procs[0].pid
            envs._procs[0].kill()
            envs._procs[0].join(timeout=5)

            out = envs.step(np.zeros((4,), np.int64))
            # dead slice (envs 0..1) came back as fresh initial outputs
            assert bool(out.done[0]) and bool(out.done[1])
            assert int(out.info.episode_step[0]) == 0
            # the healthy worker's slice kept its in-flight episodes
            assert int(out.info.episode_step[2]) > 0
            assert envs.total_respawns == 1
            assert envs._generations[0] == 1
            assert envs._procs[0].pid != old_pid

            # training keeps flowing: further steps work and episodes
            # complete on BOTH slices
            for _ in range(8):
                out = envs.step(np.zeros((4,), np.int64))
            assert len(envs.episode_stats) > 0
        finally:
            envs.close()

    def test_respawned_worker_reseeds(self):
        envs = make_envs(n=2, workers=1, episode_length=100)
        try:
            first = envs.initial()
            frame_before = np.asarray(first.observation.frame[0]).copy()
            envs._procs[0].kill()
            envs._procs[0].join(timeout=5)
            out = envs.step(np.zeros((2,), np.int64))
            # generation-shifted seed -> different initial frame pattern
            # (FakeEnv encodes its seed into the frame base value)
            frame_after = np.asarray(out.observation.frame[0])
            assert not np.array_equal(frame_before, frame_after)
        finally:
            envs.close()

    def test_respawn_budget_exhaustion_raises(self):
        envs = make_envs(max_respawns=0)
        try:
            envs.initial()
            envs._procs[0].kill()
            envs._procs[0].join(timeout=5)
            with pytest.raises(RemoteEnvError, match="crash-looping"):
                envs.step(np.zeros((4,), np.int64))
        finally:
            envs.close()

    def test_deaths_outside_window_do_not_exhaust_budget(self):
        """The budget detects crash loops, not lifetime faults: deaths
        older than respawn_window_s fall out of the window."""
        envs = make_envs(max_respawns=1)
        envs.respawn_window_s = 0.2
        try:
            for _ in range(3):  # 3 deaths, each in its own window
                envs.initial()
                envs._procs[0].kill()
                envs._procs[0].join(timeout=5)
                envs.step(np.zeros((4,), np.int64))  # respawns, no raise
                time.sleep(0.25)
            assert envs.total_respawns == 3
        finally:
            envs.close()


class TestTrainingSurvivesKill:
    def test_actor_pool_trains_through_worker_death(self):
        T, B = 4, 4
        agent = ImpalaAgent(num_actions=NUM_ACTIONS)
        groups = [make_envs(B, workers=2) for _ in range(2)]
        mesh = make_mesh(MeshSpec(data=4, model=1),
                         devices=jax.devices()[:4])
        learner = Learner(agent, LearnerHyperparams(
            total_environment_frames=1e6), mesh,
            frames_per_update=T * B)
        envs_probe = groups[0]
        out0 = envs_probe.initial()
        params = agent.init(
            jax.random.key(0),
            np.zeros((1, B), np.int32),
            jax.tree_util.tree_map(
                lambda x: None if x is None else np.asarray(x)[None],
                out0, is_leaf=lambda x: x is None),
            agent_mod.initial_state(B))
        pool = ActorPool(agent, groups, unroll_length=T, seed=3)
        pool.set_params(params)
        pool.start()
        try:
            state = None
            for update in range(6):
                out = pool.get_trajectory(timeout=120)
                traj = Trajectory(out.agent_state, out.env_outputs,
                                  out.agent_outputs)
                if state is None:
                    state = learner.init(jax.random.key(1), traj)
                state, metrics = learner.update(
                    state, learner.put_trajectory(traj))
                pool.set_params(state.params)
                if update == 1:
                    # kill one worker of each group mid-unroll
                    for g in groups:
                        g._procs[0].kill()
            assert np.isfinite(float(np.asarray(metrics["total_loss"])))
            assert sum(g.total_respawns for g in groups) >= 1
            assert len(pool.episode_stats()) > 0
        finally:
            pool.stop()
