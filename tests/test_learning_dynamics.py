"""ISSUE 17: the learning-dynamics plane.

Device side: V-trace/IMPACT clip diagnostics (golden fractions on a
hand-built off-policy batch), the loss path's entropy/KL/explained-
variance, per-layer-group optimizer telemetry — and THE acceptance
property: the instrumented update issues zero host syncs (transfer
guard + materialization spies), including all K updates of a
``--updates_per_dispatch=K`` megaloop dispatch.

Host side: the jax-free obs/learning.py rules, the ``obs.diagnose``
CLI over synthetic and real driver artifacts, the report/watch
learning sections, the fleet fold rules for devtel/learn series, and
the chaos e2e — an oversized-lr driver run must trip the
``entropy_collapse`` anomaly (with a pinned flightrec dump) and the
matching diagnose verdict while the sane twin stays verdict-clean.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalable_agent_tpu.obs import (
    MetricsRegistry,
    get_registry,
    render_prometheus,
)
from scalable_agent_tpu.obs import learning
from scalable_agent_tpu.obs.aggregate import (
    aggregate_prometheus,
    parse_prometheus,
)
from scalable_agent_tpu.obs.diagnose import (
    build_diagnosis,
    render_diagnosis,
)
from scalable_agent_tpu.obs.diagnose import main as diagnose_main
from scalable_agent_tpu.ops.impact import surrogate_from_logits
from scalable_agent_tpu.ops.vtrace import (
    from_importance_weights,
    importance_diagnostics,
)

NUM_ACTIONS = 4


# ---------------------------------------------------------------------------
# Golden clip-fraction diagnostics (ops layer).
# ---------------------------------------------------------------------------


class TestImportanceDiagnostics:
    def test_on_policy_batch_reports_zero_everywhere(self):
        d = importance_diagnostics(np.zeros((5, 4), np.float32))
        assert float(d.rho_clip_fraction) == 0.0
        assert float(d.cs_clip_fraction) == 0.0
        assert float(d.pg_rho_clip_fraction) == 0.0
        assert float(d.log_rho_mean) == 0.0
        assert float(d.log_rho_p95) == 0.0
        assert float(d.ess_frac) == pytest.approx(1.0)

    def test_golden_fractions_on_hand_built_batch(self):
        """rhos [0.5, 1.0, 2.0, 4.0] against rho-bar=1: exactly the two
        rhos ABOVE the threshold count (strict >, the value exactly at
        the bar is returned unchanged by the clip)."""
        rhos = np.asarray([0.5, 1.0, 2.0, 4.0], np.float64)
        d = importance_diagnostics(np.log(rhos).astype(np.float32))
        assert float(d.rho_clip_fraction) == pytest.approx(0.5)
        assert float(d.cs_clip_fraction) == pytest.approx(0.5)
        assert float(d.pg_rho_clip_fraction) == pytest.approx(0.5)
        assert float(d.log_rho_mean) == pytest.approx(
            np.log(rhos).mean(), rel=1e-5)
        assert float(d.log_rho_p95) == pytest.approx(
            np.quantile(np.log(rhos), 0.95), rel=1e-5)
        want_ess = rhos.sum() ** 2 / (len(rhos) * (rhos ** 2).sum())
        assert float(d.ess_frac) == pytest.approx(want_ess, rel=1e-5)

    def test_ess_survives_extreme_log_rhos(self):
        """exp(2*log_rho) overflows f32 from log_rho ~ 44; the ESS is
        scale-invariant so the max-shifted form must stay finite (a
        single rogue trajectory must not NaN the gauge)."""
        d = importance_diagnostics(np.full((4, 2), 50.0, np.float32))
        # All weights equal => ESS is exactly 1 regardless of scale.
        assert float(d.ess_frac) == pytest.approx(1.0)
        mixed = np.zeros((4, 2), np.float32)
        mixed[0, 0] = 80.0  # one weight utterly dominates: ESS -> 1/N
        d2 = importance_diagnostics(mixed)
        assert float(d2.ess_frac) == pytest.approx(1.0 / mixed.size)

    def test_custom_and_none_thresholds(self):
        rhos = np.asarray([0.5, 1.5, 2.5, 4.0], np.float64)
        log_rhos = np.log(rhos).astype(np.float32)
        d = importance_diagnostics(log_rhos, clip_rho_threshold=2.5,
                                   clip_pg_rho_threshold=None)
        # Only 4.0 exceeds 2.5 (2.5 itself is AT the bar, not over it).
        assert float(d.rho_clip_fraction) == pytest.approx(0.25)
        assert float(d.pg_rho_clip_fraction) == 0.0  # clip disabled
        # The c-bar is always 1.0: three rhos exceed it.
        assert float(d.cs_clip_fraction) == pytest.approx(0.75)

    def test_vtrace_returns_carry_the_diagnostics(self):
        T, B = 6, 3
        rng = np.random.default_rng(0)
        log_rhos = rng.normal(scale=0.5, size=(T, B)).astype(np.float32)
        out = from_importance_weights(
            log_rhos=log_rhos,
            discounts=np.full((T, B), 0.9, np.float32),
            rewards=rng.normal(size=(T, B)).astype(np.float32),
            values=rng.normal(size=(T, B)).astype(np.float32),
            bootstrap_value=rng.normal(size=(B,)).astype(np.float32))
        assert out.diagnostics is not None
        want = importance_diagnostics(log_rhos)
        for field in want._fields:
            assert float(getattr(out.diagnostics, field)) == (
                pytest.approx(float(getattr(want, field)), abs=1e-6)), field


class TestImpactDiagnostics:
    def _logits(self, scale=0.0, seed=1):
        rng = np.random.default_rng(seed)
        online = rng.normal(size=(5, 4, NUM_ACTIONS)).astype(np.float32)
        target = online + rng.normal(
            scale=scale, size=online.shape).astype(np.float32)
        actions = rng.integers(0, NUM_ACTIONS, size=(5, 4))
        adv = rng.normal(size=(5, 4)).astype(np.float32)
        return online, target, actions.astype(np.int32), adv

    def test_anchored_online_net_is_exactly_on_target(self):
        online, _, actions, adv = self._logits()
        out = surrogate_from_logits(online, online, actions, adv)
        assert float(out.ratio_mean) == pytest.approx(1.0)
        assert float(out.clip_fraction) == 0.0
        assert float(out.log_ratio_mean) == pytest.approx(0.0, abs=1e-6)
        assert float(out.log_ratio_p95) == pytest.approx(0.0, abs=1e-6)
        assert float(out.ess_frac) == pytest.approx(1.0)

    def test_drifted_online_net_reports_the_tail(self):
        online, target, actions, adv = self._logits(scale=1.0)
        out = surrogate_from_logits(online, target, actions, adv)
        from scalable_agent_tpu.ops import distributions

        spec = distributions.DistributionSpec(sizes=(NUM_ACTIONS,))
        log_ratio = np.asarray(
            distributions.log_prob(online, actions, spec)
            - distributions.log_prob(target, actions, spec))
        assert float(out.log_ratio_mean) == pytest.approx(
            log_ratio.mean(), abs=1e-5)
        assert float(out.log_ratio_p95) == pytest.approx(
            np.quantile(log_ratio, 0.95), abs=1e-4)
        r = np.exp(log_ratio.astype(np.float64))
        want_ess = r.sum() ** 2 / (r.size * (r ** 2).sum())
        assert float(out.ess_frac) == pytest.approx(want_ess, rel=1e-4)
        assert 0.0 < float(out.ess_frac) < 1.0


# ---------------------------------------------------------------------------
# The jax-free rule pass (obs/learning.py).
# ---------------------------------------------------------------------------


HEALTHY = {
    "entropy_frac": 0.7, "kl": 0.01, "ess_frac": 0.9,
    "explained_variance": 0.5, "rho_clip_fraction": 0.1,
    "dead_torso_frac": 0.05, "update_ratio_torso": 1e-3,
    "update_ratio_core": 1e-3, "update_ratio_heads": 1e-3,
}


class TestLearningRules:
    def test_healthy_snapshot_is_clean(self):
        assert learning.derive_verdicts(HEALTHY) == []

    def test_empty_snapshot_is_clean_not_broken(self):
        assert learning.derive_verdicts({}) == []

    def _fired(self, overrides):
        snapshot = {**HEALTHY, **overrides}
        return [v["name"] for v in learning.derive_verdicts(snapshot)]

    def test_entropy_collapse(self):
        assert self._fired({"entropy_frac": 0.01}) == ["entropy_collapse"]
        assert self._fired({"entropy_frac": 0.06}) == []

    def test_value_divergence_allows_warmup_negative_ev(self):
        assert self._fired({"explained_variance": -0.8}) == [
            "value_divergence"]
        # Mildly negative EV is a warming-up critic, not divergence.
        assert self._fired({"explained_variance": -0.1}) == []

    def test_off_policy_saturated_via_clip_or_ess(self):
        verdicts = learning.derive_verdicts(
            {**HEALTHY, "rho_clip_fraction": 0.95})
        assert [v["name"] for v in verdicts] == ["off_policy_saturated"]
        assert "replay_ratio" in verdicts[0]["remedy"]
        assert "target_update_interval" in verdicts[0]["remedy"]
        assert self._fired({"ess_frac": 0.05}) == ["off_policy_saturated"]

    def test_update_ratio_fires_high_only(self):
        fired = learning.derive_verdicts(
            {**HEALTHY, "update_ratio_core": 0.5})
        assert [v["name"] for v in fired] == ["update_ratio_out_of_band"]
        assert fired[0]["evidence"]["group"] == "core"
        # The lr schedule anneals the ratio to zero at end of run: a
        # tiny ratio must NOT be a verdict.
        assert self._fired({"update_ratio_heads": 0.0}) == []

    def test_dead_torso(self):
        assert self._fired({"dead_torso_frac": 0.95}) == ["dead_torso"]
        # Tiny fake-env batches legitimately idle half the torso.
        assert self._fired({"dead_torso_frac": 0.6}) == []

    def test_extract_snapshot_filters_nonfinite(self):
        snap = learning.extract_snapshot({
            "devtel/learn/entropy_frac": 0.5,
            "devtel/learn/kl": float("nan"),
            "devtel/learn/ess_frac": None,
            "unrelated/metric": 1.0})
        assert snap == {"entropy_frac": 0.5}


class TestStalenessClipRelationship:
    S_KEY = "ledger/staleness_replayed_s/p95"
    C_KEY = "devtel/learn/rho_clip_fraction"

    def _rows(self, pairs):
        return [{self.S_KEY: s, self.C_KEY: c} for s, c in pairs]

    def test_positive_correlation_measured(self):
        rows = self._rows([(0.1, 0.05), (0.5, 0.2), (1.0, 0.4),
                           (2.0, 0.75)])
        out = learning.staleness_clip_relationship(rows)
        assert out["intervals"] == 4
        assert out["pearson_r"] > 0.95
        assert out["clip_per_staleness_s"] > 0.0
        assert "correlate" in out["statement"]

    def test_too_few_points_or_constant_series_is_none(self):
        assert learning.staleness_clip_relationship(
            self._rows([(0.1, 0.1), (0.2, 0.2)])) is None
        assert learning.staleness_clip_relationship(
            self._rows([(0.5, 0.1), (0.5, 0.2), (0.5, 0.3)])) is None

    def test_rows_missing_either_series_are_skipped(self):
        rows = self._rows([(0.1, 0.05), (0.5, 0.2), (1.0, 0.4)])
        rows.insert(1, {self.S_KEY: 0.3})  # no clip reading
        out = learning.staleness_clip_relationship(rows)
        assert out["intervals"] == 3

    def test_read_interval_rows_strips_prefix_and_skips_torn(
            self, tmp_path):
        path = tmp_path / "metrics.jsonl"
        rows = [
            {"step": 1, "obs/devtel/learn/rho_clip_fraction": 0.1,
             "obs/ledger/staleness_replayed_s/p95": 0.2,
             "total_loss": 3.0},
            {"step": 2, "obs/devtel/learn/rho_clip_fraction": 0.3},
        ]
        text = "\n".join(json.dumps(r) for r in rows)
        path.write_text(text + '\n{"step": 3, "obs/trunc')  # torn tail
        parsed = learning.read_interval_rows(str(tmp_path))
        assert len(parsed) == 2
        assert parsed[0]["devtel/learn/rho_clip_fraction"] == 0.1
        assert parsed[0]["ledger/staleness_replayed_s/p95"] == 0.2
        assert parsed[0]["step"] == 1
        assert "total_loss" not in parsed[0]  # only obs/ rows


# ---------------------------------------------------------------------------
# Learner integration: in-graph stats + zero-host-sync acceptance.
# ---------------------------------------------------------------------------


def _small_learner(loss="vtrace"):
    from __graft_entry__ import _example_trajectory
    from scalable_agent_tpu.models import ImpalaAgent
    from scalable_agent_tpu.parallel import MeshSpec, make_mesh
    from scalable_agent_tpu.runtime import Learner, LearnerHyperparams

    T, B = 4, 2
    agent = ImpalaAgent(num_actions=NUM_ACTIONS)
    mesh = make_mesh(MeshSpec(data=1, model=1),
                     devices=jax.devices()[:1])
    learner = Learner(agent, LearnerHyperparams(
        total_environment_frames=1e6), mesh, frames_per_update=T * B,
        loss=loss)
    traj_host = _example_trajectory(T, B, 16, 16, NUM_ACTIONS)
    state = learner.init(jax.random.key(0), traj_host)
    traj = learner.put_trajectory(traj_host)
    return learner, state, traj


@pytest.fixture(scope="module")
def vtrace_setup():
    learner, state, traj = _small_learner("vtrace")
    return {"learner": learner, "state": state, "traj": traj}


@pytest.fixture(scope="module")
def impact_setup():
    learner, state, traj = _small_learner("impact")
    return {"learner": learner, "state": state, "traj": traj}


class TestLearnerPlane:
    def test_update_metrics_carry_learning_stats(self, vtrace_setup):
        learner, traj = vtrace_setup["learner"], vtrace_setup["traj"]
        state, metrics = learner.update(vtrace_setup["state"], traj)
        vtrace_setup["state"] = state
        for key in ("policy_entropy", "entropy_frac", "behaviour_kl",
                    "explained_variance", "rho_clip_fraction",
                    "cs_clip_fraction", "pg_rho_clip_fraction",
                    "log_rho_mean", "log_rho_p95", "ess_frac",
                    "dead_torso_frac"):
            assert key in metrics, key
        assert 0.0 < float(np.asarray(metrics["entropy_frac"])) <= 1.0
        assert 0.0 < float(np.asarray(metrics["ess_frac"])) <= 1.0
        assert 0.0 <= float(np.asarray(metrics["dead_torso_frac"])) < 1.0
        assert float(np.asarray(metrics["behaviour_kl"])) >= 0.0

    def test_gauges_published_under_devtel_learn(self, vtrace_setup):
        learner, traj = vtrace_setup["learner"], vtrace_setup["traj"]
        state, metrics = learner.update(vtrace_setup["state"], traj)
        vtrace_setup["state"] = state
        fetched = learner.publish_device_telemetry()
        lspec = learner.learn_spec
        # Every instrument of the plane must come back in the one
        # merged fetch (red side: a key the spec declares but the
        # update never writes would still appear — value defaults — so
        # ALSO pin the gauge mirrors the last update's metric exactly).
        for name in lspec.gauges():
            assert lspec.value(fetched, name) is not None, name
        assert lspec.value(fetched, "entropy_frac") == pytest.approx(
            float(np.asarray(metrics["entropy_frac"])), rel=1e-6)
        assert lspec.value(fetched, "ess_frac") == pytest.approx(
            float(np.asarray(metrics["ess_frac"])), rel=1e-6)
        for group in ("torso", "core", "heads"):
            assert lspec.value(fetched, f"param_norm_{group}") > 0.0
            assert lspec.value(fetched, f"update_ratio_{group}") >= 0.0
        snap = get_registry().snapshot()
        assert "devtel/learn/entropy_frac" in snap
        assert "devtel/learn/update_ratio_core" in snap

    def test_vtrace_updates_issue_no_host_syncs(self, vtrace_setup):
        """THE zero-added-sync acceptance (ISSUE 17): the fully
        instrumented update — clip diagnostics, entropy/KL/EV, dead
        units, per-group norms — materializes nothing on the host; the
        log-interval fetch stays the only sync."""
        from scalable_agent_tpu.envs.device.conformance import (
            materialization_spy)

        learner, traj = vtrace_setup["learner"], vtrace_setup["traj"]
        state, _ = learner.update(vtrace_setup["state"], traj)  # warm
        with materialization_spy() as calls:
            with jax.transfer_guard("disallow"):
                for _ in range(3):
                    state, _ = learner.update(state, traj)
            assert calls == [], (
                f"learning-telemetry updates materialized device "
                f"values on the host: {calls}")
            vtrace_setup["state"] = state
            learner.fetch_device_telemetry()
            assert calls, "the explicit fetch IS the sync"

    def test_impact_updates_issue_no_host_syncs(self, impact_setup):
        from scalable_agent_tpu.envs.device.conformance import (
            materialization_spy)

        learner, traj = impact_setup["learner"], impact_setup["traj"]
        state, _ = learner.update(impact_setup["state"], traj)  # warm
        with materialization_spy() as calls:
            with jax.transfer_guard("disallow"):
                for _ in range(3):
                    state, _ = learner.update(state, traj)
            assert calls == []
        impact_setup["state"] = state

    def test_impact_histograms_aggregate_across_updates(
            self, impact_setup):
        learner, traj = impact_setup["learner"], impact_setup["traj"]
        state = impact_setup["state"]
        before = learner.fetch_device_telemetry()
        lspec = learner.learn_spec
        count0 = lspec.value(before, "impact_ratio")["count"]
        for _ in range(3):
            state, metrics = learner.update(state, traj)
        impact_setup["state"] = state
        fetched = learner.fetch_device_telemetry()
        hist = lspec.value(fetched, "impact_ratio")
        assert hist["count"] == count0 + 3
        clip_hist = lspec.value(fetched, "impact_clip_fraction")
        assert clip_hist["count"] >= 3
        assert lspec.value(fetched, "impact_ess_frac") == pytest.approx(
            float(np.asarray(metrics["impact_ess_frac"])), rel=1e-6)
        # The per-update ratio is ~1 (the online net hugs its anchor).
        assert hist["mean"] == pytest.approx(1.0, abs=0.2)

    def test_disabled_plane_is_inert(self):
        from __graft_entry__ import _example_trajectory
        from scalable_agent_tpu.models import ImpalaAgent
        from scalable_agent_tpu.parallel import MeshSpec, make_mesh
        from scalable_agent_tpu.runtime import (
            Learner, LearnerHyperparams)

        agent = ImpalaAgent(num_actions=NUM_ACTIONS)
        mesh = make_mesh(MeshSpec(data=1, model=1),
                         devices=jax.devices()[:1])
        learner = Learner(agent, LearnerHyperparams(), mesh,
                          frames_per_update=8, learn_telemetry=False)
        traj = _example_trajectory(4, 2, 16, 16, NUM_ACTIONS)
        state = learner.init(jax.random.key(0), traj)
        state, metrics = learner.update(state, traj)
        assert "entropy_frac" not in metrics
        assert learner.learn_spec.empty
        fetched = learner.fetch_device_telemetry()
        assert not any(k.startswith("g:learn/") for k in fetched)


class TestMegaloopAggregation:
    """``--updates_per_dispatch=K``: one device dispatch runs K fused
    updates; the learn histograms must cover ALL K (the metrics dict
    only surfaces the last scan iteration's scalars)."""

    T, B = 5, 4
    K = 4

    def make(self):
        from scalable_agent_tpu.envs.device import DeviceFakeEnv
        from scalable_agent_tpu.models import ImpalaAgent
        from scalable_agent_tpu.parallel import MeshSpec, make_mesh
        from scalable_agent_tpu.runtime import (
            Learner, LearnerHyperparams)
        from scalable_agent_tpu.runtime.ingraph import InGraphTrainer

        agent = ImpalaAgent(num_actions=NUM_ACTIONS)
        mesh = make_mesh(MeshSpec(data=1, model=1),
                         devices=jax.devices()[:1])
        learner = Learner(agent, LearnerHyperparams(
            total_environment_frames=1e6), mesh,
            frames_per_update=self.T * self.B, loss="impact")
        env = DeviceFakeEnv(height=12, width=12,
                            num_actions=NUM_ACTIONS, episode_length=7)
        return InGraphTrainer(agent, learner, env, self.T, self.B,
                              seed=5, updates_per_dispatch=self.K,
                              ), learner

    def test_one_dispatch_observes_all_k_updates(self):
        trainer, learner = self.make()
        state, carry = trainer.init(jax.random.key(0))
        state, carry, _ = trainer.run(state, carry, self.K)
        fetched = trainer.fetch_telemetry(carry)
        lspec = learner.learn_spec
        for hist in ("impact_ratio", "impact_clip_fraction"):
            assert lspec.value(fetched, hist)["count"] == self.K, hist
        # Gauges carry the last update of the fused scan.
        assert 0.0 < lspec.value(fetched, "entropy_frac") <= 1.0

    def test_fused_dispatch_issues_no_host_syncs(self):
        """The K-update dispatch adds no host sync beyond the update
        counter (a pre-existing per-dispatch input, placed on device
        here so the guard sees only what the learning plane added)."""
        from scalable_agent_tpu.envs.device.conformance import (
            materialization_spy)

        trainer, _ = self.make()
        state, carry = trainer.init(jax.random.key(0))
        counters = [jax.device_put(np.int32(i * self.K))
                    for i in range(3)]
        # Warm the device-counter call signature outside the guard.
        state, carry, _ = trainer.train_step(
            state, carry, counters[0])[:3]
        with materialization_spy() as calls:
            with jax.transfer_guard("disallow"):
                for counter in counters[1:]:
                    state, carry, _ = trainer.train_step(
                        state, carry, counter)[:3]
            assert calls == [], (
                f"the megaloop dispatch materialized device values on "
                f"the host: {calls}")


# ---------------------------------------------------------------------------
# Fleet folds for the new series.
# ---------------------------------------------------------------------------


class TestLearnFleetFolds:
    def _fold(self, metric, values, mtype="gauge"):
        texts = {
            str(i): (f"# TYPE {metric} {mtype}\n{metric} {v}\n")
            for i, v in enumerate(values)}
        families = parse_prometheus(aggregate_prometheus(texts))
        for fam, data in families.items():
            for (name, labels), value in data["series"].items():
                if name == metric and dict(labels).get("fold"):
                    return value, dict(labels)["fold"]
        raise AssertionError(f"no fleet series for {metric}")

    def test_low_is_bad_gauges_fold_min(self):
        """The fleet reading of entropy/ESS/EV keeps the SICKEST
        process — a healthy peer must not mask a collapsing one."""
        for metric in ("impala_devtel_learn_entropy_frac",
                       "impala_devtel_learn_ess_frac",
                       "impala_devtel_learn_explained_variance"):
            value, fold = self._fold(metric, [0.9, 0.2])
            assert (value, fold) == (0.2, "min"), metric

    def test_high_is_bad_gauges_fold_max(self):
        for metric in ("impala_devtel_learn_rho_clip_fraction",
                       "impala_devtel_learn_kl",
                       "impala_devtel_learn_dead_torso_frac",
                       "impala_devtel_learn_update_ratio_core"):
            value, fold = self._fold(metric, [0.1, 0.7])
            assert (value, fold) == (0.7, "max"), metric

    def test_impact_bucket_counters_sum(self):
        metric = ("impala_devtel_learn_impact_ratio_bucket_le_1_total")
        value, fold = self._fold(metric, [3.0, 5.0], mtype="counter")
        assert (value, fold) == (8.0, "sum")


# ---------------------------------------------------------------------------
# obs.diagnose / obs.report / obs.watch over on-disk artifacts.
# ---------------------------------------------------------------------------


def _write_snapshot(logdir, overrides=(), extra=None):
    os.makedirs(logdir, exist_ok=True)
    readings = {**HEALTHY,
                "cs_clip_fraction": 0.1, "pg_rho_clip_fraction": 0.1,
                "log_rho_mean": 0.02, "log_rho_p95": 0.3,
                "grad_norm_torso": 1.0, "grad_norm_core": 1.0,
                "grad_norm_heads": 1.0, "param_norm_torso": 20.0,
                "param_norm_core": 40.0, "param_norm_heads": 3.0,
                **dict(overrides)}
    registry = MetricsRegistry()
    for short, value in readings.items():
        registry.gauge(learning.LEARNING_GAUGES[short], "test").set(value)
    for name, value in (extra or {}).items():
        registry.gauge(name, "test").set(value)
    with open(os.path.join(logdir, "metrics.prom"), "w") as f:
        f.write(render_prometheus(registry))
    return readings


class TestDiagnoseCLI:
    def test_clean_run_exits_zero(self, tmp_path, capsys):
        _write_snapshot(tmp_path)
        assert diagnose_main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "verdict: clean" in out
        assert "entropy (normalized)" in out
        assert "layer group" in out and "update/param" in out

    def test_collapsed_run_exits_one_and_names_the_anomaly(
            self, tmp_path, capsys):
        _write_snapshot(tmp_path, overrides={"entropy_frac": 0.004})
        record = {"id": "a001-entropy_collapse",
                  "detector": "entropy_collapse", "update": 12,
                  "observed": 0.004,
                  "flightrec": {"dump": "health:a001-entropy_collapse"},
                  "window": {"status": "closed"}}
        (tmp_path / "anomalies.jsonl").write_text(
            json.dumps(record) + "\n")
        assert diagnose_main([str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "entropy_collapse" in out
        assert "a001-entropy_collapse" in out
        assert "flightrec dump: health:a001-entropy_collapse" in out
        assert "raise --entropy_cost" in out

    def test_missing_logdir_exits_two(self, tmp_path, capsys):
        assert diagnose_main([str(tmp_path / "nope")]) == 2
        assert "obs.diagnose" in capsys.readouterr().err

    def test_json_payload_round_trips(self, tmp_path, capsys):
        _write_snapshot(tmp_path, overrides={"ess_frac": 0.02})
        assert diagnose_main([str(tmp_path), "--json"]) == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["clean"] is False
        assert [v["name"] for v in payload["verdicts"]] == [
            "off_policy_saturated"]

    def test_impact_anchor_line_renders(self, tmp_path):
        _write_snapshot(tmp_path, extra={
            "devtel/learn/impact_ratio/mean": 1.01,
            "devtel/learn/impact_ratio/count": 64.0,
            "devtel/learn/impact_clip_fraction/mean": 0.12,
            "devtel/learn/impact_log_ratio_p95": 0.2,
            "devtel/learn/impact_ess_frac": 0.95})
        diagnosis = build_diagnosis(str(tmp_path))
        assert diagnosis["impact"]["updates_observed"] == 64.0
        text = render_diagnosis(diagnosis)
        assert "IMPACT anchor: ratio mean 1.0100" in text
        assert "over 64 updates" in text

    def test_staleness_clip_statement_from_interval_rows(
            self, tmp_path):
        """Satellite 2: the report/diagnose correlate the ledger's
        replayed-staleness series with the clip-fraction series across
        intervals and state the measured relationship."""
        _write_snapshot(tmp_path)
        rows = [
            {"step": i,
             "obs/ledger/staleness_replayed_s/p95": 0.1 * i,
             "obs/devtel/learn/rho_clip_fraction": 0.05 + 0.08 * i}
            for i in range(1, 6)]
        (tmp_path / "metrics.jsonl").write_text(
            "\n".join(json.dumps(r) for r in rows) + "\n")
        diagnosis = build_diagnosis(str(tmp_path))
        relation = diagnosis["staleness_clip"]
        assert relation["intervals"] == 5
        assert relation["pearson_r"] == pytest.approx(1.0, abs=1e-6)
        assert "staleness→clipping:" in render_diagnosis(diagnosis)


class TestReportAndWatchSections:
    def test_report_carries_learning_section(self, tmp_path):
        from scalable_agent_tpu.obs.report import (
            build_report, render_report)

        _write_snapshot(tmp_path, overrides={"entropy_frac": 0.004})
        report = build_report(str(tmp_path))
        section = report["learning"]
        assert section["snapshot"]["entropy_frac"] == pytest.approx(
            0.004)
        assert [v["name"] for v in section["verdicts"]] == [
            "entropy_collapse"]
        text = render_report(str(tmp_path))
        assert "learning dynamics" in text
        assert "entropy_collapse" in text

    def test_watch_payload_carries_learning_panel(self, tmp_path):
        from scalable_agent_tpu.obs.watch import build_payload, render

        _write_snapshot(tmp_path, overrides={"rho_clip_fraction": 0.97})
        payload = build_payload(str(tmp_path))
        panel = payload["learning"]
        assert panel["snapshot"]["rho_clip_fraction"] == pytest.approx(
            0.97)
        assert [v["name"] for v in panel["verdicts"]] == [
            "off_policy_saturated"]
        text = render(payload)
        assert "learning" in text
        assert "!! off_policy_saturated" in text

    def test_runs_without_the_plane_render_none(self, tmp_path):
        from scalable_agent_tpu.obs.report import build_report
        from scalable_agent_tpu.obs.watch import build_payload

        os.makedirs(tmp_path, exist_ok=True)
        registry = MetricsRegistry()
        registry.gauge("learner/fps", "t").set(100.0)
        (tmp_path / "metrics.prom").write_text(
            render_prometheus(registry))
        assert build_report(str(tmp_path))["learning"] is None
        assert build_payload(str(tmp_path))["learning"] is None


# ---------------------------------------------------------------------------
# Chaos e2e: the oversized-lr run trips entropy_collapse; the sane twin
# stays clean.
# ---------------------------------------------------------------------------


def _driver_config(tmp_path, name, **overrides):
    from scalable_agent_tpu.config import Config

    defaults = dict(
        mode="train",
        logdir=str(tmp_path / name),
        level_name="fake_small",
        num_actors=4,
        batch_size=2,
        unroll_length=4,
        num_action_repeats=1,
        total_environment_frames=80,
        height=16,
        width=16,
        num_env_workers_per_group=2,
        compute_dtype="float32",
        checkpoint_interval_s=0.0,
        log_interval_s=0.0,
        seed=5,
    )
    defaults.update(overrides)
    return Config(**defaults)


class TestChaosEntropyCollapse:
    def test_oversized_lr_trips_the_verdict_sane_twin_clean(
            self, tmp_path):
        """ISSUE 17 chaos e2e: a driver run with a divergence-scale lr
        and an inverted entropy bonus collapses the policy; the health
        plane must write an ``entropy_collapse`` anomaly record with a
        pinned flightrec dump, and ``obs.diagnose`` must name it.  The
        identical sane config stays verdict-clean — same shapes, so
        the second run rides the first one's jit cache."""
        from scalable_agent_tpu.driver import train as run_train
        from scalable_agent_tpu.obs.health import read_anomalies

        bad = _driver_config(tmp_path, "bad", learning_rate=0.5,
                             entropy_cost=-5.0)
        run_train(bad)
        records = read_anomalies(bad.logdir)
        collapse = [r for r in records
                    if r.get("detector") == "entropy_collapse"]
        assert collapse, (
            f"no entropy_collapse anomaly; detectors seen: "
            f"{[r.get('detector') for r in records]}")
        assert (collapse[-1].get("flightrec") or {}).get("dump"), (
            "the collapse anomaly must pin a flight-recorder dump")
        diagnosis = build_diagnosis(bad.logdir)
        names = [v["name"] for v in diagnosis["verdicts"]]
        assert "entropy_collapse" in names
        verdict = diagnosis["verdicts"][names.index("entropy_collapse")]
        # The verdict links the anomaly record the plane wrote live.
        assert any(a.get("flightrec", {}).get("dump")
                   for a in verdict["anomalies"])
        assert diagnose_main([bad.logdir]) == 1

        sane = _driver_config(tmp_path, "sane")
        run_train(sane)
        sane_diag = build_diagnosis(sane.logdir)
        assert sane_diag["clean"], (
            f"sane run fired: {sane_diag['verdicts']}")
        assert not [r for r in read_anomalies(sane.logdir)
                    if r.get("detector") in ("entropy_collapse",
                                             "clip_saturation")]
        assert diagnose_main([sane.logdir]) == 0
