"""ISSUE 2 acceptance: SIGTERM to a traced driver run leaves a loadable
``flightrec.<pid>.json`` (plus the stack dump and a final metrics
snapshot) — the signal path through driver._setup_observability's crash
handlers, exercised against the REAL driver in a subprocess.

``--preemption_grace_s=0`` pins the LEGACY dump-and-exit(143) contract
this test owns; with the grace protocol enabled (the default since the
fleet layer, runtime/fleet.py) SIGTERM instead drains to a final
checkpoint and exits 0 — covered by tests/test_fleet_multiproc.py."""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import pytest


def test_sigterm_to_traced_driver_leaves_flight_recorder(tmp_path):
    logdir = str(tmp_path / "run")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # A run sized to keep producing updates until killed: the frame
    # target is far beyond what the subprocess will reach.
    proc = subprocess.Popen(
        [sys.executable, "-m", "scalable_agent_tpu.driver",
         "--mode=train", "--level_name=fake_small", "--logdir", logdir,
         "--num_actors=4", "--batch_size=2", "--unroll_length=4",
         "--num_action_repeats=1", "--total_environment_frames=1000000",
         "--height=16", "--width=16", "--num_env_workers_per_group=2",
         "--compute_dtype=float32", "--checkpoint_interval_s=1e9",
         "--log_interval_s=0.2", "--trace=true", "--seed=3",
         "--preemption_grace_s=0"],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        # Wait until the run demonstrably trains (metrics rows flowing),
        # so the SIGTERM lands mid-pipeline, not during imports.
        jsonl = os.path.join(logdir, "metrics.jsonl")
        deadline = time.monotonic() + 150
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                pytest.fail("driver exited early:\n"
                            + proc.stdout.read()[-3000:])
            if os.path.exists(jsonl) and os.path.getsize(jsonl) > 0:
                break
            time.sleep(0.25)
        else:
            pytest.fail("driver produced no metrics before the deadline")
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert proc.returncode == 128 + signal.SIGTERM, proc.returncode
    # The flight-recorder dump is loadable and names the signal.
    (flight_path,) = glob.glob(os.path.join(logdir, "flightrec.*.json"))
    payload = json.load(open(flight_path))
    assert payload["reason"] == "signal:SIGTERM"
    kinds = {e["kind"] for e in payload["events"]}
    # The ring saw the pipeline run: queue hand-offs and update steps
    # (and spans, since --trace was on).
    assert "queue" in kinds and "update" in kinds and "span" in kinds
    # All-thread stacks and a final metrics snapshot rode along.
    (stacks_path,) = glob.glob(os.path.join(logdir, "stacks.*.txt"))
    assert os.path.getsize(stacks_path) > 0
    assert "impala_learner_updates_total" in open(
        os.path.join(logdir, "metrics.prom")).read()
    # The SystemExit raised by the handler unwound through train()'s
    # finally: the trace tail was flushed and remains loadable.
    from scalable_agent_tpu.obs import load_trace_events

    (trace_path,) = glob.glob(os.path.join(logdir, "trace.p0.*.json"))
    assert any(e.get("ph") == "X" for e in load_trace_events(trace_path))
