"""End-to-end learning proofs: return must RISE through the real stack.

The reference's published capability is learning curves (reference:
README.md:36-44: return 200-250 on explore_goal_locations_small;
README.md:46-56: DMLab-30 suite score) — not just throughput.  These
tests are the hermetic stand-in: the ``fake_bandit`` / ``fake_memory``
levels (envs/fake.py reward_mode docs) have a known uniform-random
return and a known optimal return, and training through the REAL driver
path must move mean episode return from the random floor toward the
optimum.

Red-test property (the point of the suite): two controls prove these
assertions have discriminating power —

- a sign-flipped policy-gradient advantage drives return BELOW the
  random floor (the policy learns to avoid the rewarded action), and
- a broken LSTM done-reset stalls the memory task far below where the
  healthy core is by the same update count.

So a regression that flips the advantage sign or breaks the done-reset
turns these tests red; finite-loss smoke tests never would.

Budget note: these train for real (minutes total on one CPU core), so
none are in the smoke tier.
"""

import json
import os

import numpy as np
import pytest

# fake_bandit: 16 steps/episode, 4 actions -> uniform-random return 4.0,
# optimal 16.  fake_memory: 8 steps, 4 actions -> random 2.0, optimal 8.
BANDIT_RANDOM = 4.0
MEMORY_RANDOM = 2.0


def _train_config(logdir, updates, **overrides):
    from scalable_agent_tpu.config import Config

    t, b = 16, 16
    base = dict(
        mode="train", level_name="fake_bandit", logdir=str(logdir),
        height=16, width=16, num_actors=32, batch_size=b,
        unroll_length=t, num_action_repeats=1,
        total_environment_frames=float(updates * t * b),
        learning_rate=0.002, entropy_cost=0.003,
        num_env_workers_per_group=2, log_interval_s=0.2,
        checkpoint_interval_s=3600.0)
    base.update(overrides)
    return Config(**base)


def _episode_returns(logdir):
    """[(update, mean_episode_return)] rows the run logged."""
    path = os.path.join(str(logdir), "metrics.jsonl")
    rows = [json.loads(line) for line in open(path)]
    return [(r["step"], r["episode_return"]) for r in rows
            if "episode_return" in r]


def _assert_learned(returns, random_return, updates):
    """Early window ~ random floor; late window >= 2x random and
    significantly above early."""
    assert len(returns) >= 8, f"too few episode_return rows: {returns}"
    # First/last logged rows, not update-indexed windows: metric rows
    # are wall-clock-gated (log_interval_s), so an update-count window
    # could be empty on a fast machine.
    early = np.mean([r for _, r in returns[:3]])
    late = np.mean([r for _, r in returns[-5:]])
    assert early < 1.6 * random_return, (
        f"early return {early:.2f} is not near the random floor "
        f"{random_return} — the control baseline is broken")
    assert late >= 2.0 * random_return, (
        f"final return {late:.2f} did not reach 2x the random floor "
        f"{random_return}: the system is not learning")
    assert late - early >= random_return, (
        f"return did not improve: early {early:.2f} late {late:.2f}")


@pytest.mark.slow
def test_host_driver_learns_bandit(tmp_path):
    """The full host pipeline — ActorPool, env workers, prefetch,
    Learner — improves fake_bandit return from ~4 (random) to >= 8."""
    from scalable_agent_tpu import driver

    updates = 200
    config = _train_config(tmp_path / "run", updates)
    driver.train(config)
    _assert_learned(_episode_returns(tmp_path / "run"),
                    BANDIT_RANDOM, updates)


@pytest.mark.slow
def test_bf16_compute_learns_bandit(tmp_path):
    """ISSUE 18: bf16 compute end-to-end (f32 params, bf16
    activations/matmuls, f32 loss and V-trace) learns fake_bandit
    through the same driver path and the same curve thresholds as the
    f32 run above — the low-precision policy must match the f32 curve's
    acceptance window, not merely stay finite."""
    from scalable_agent_tpu import driver

    updates = 200
    config = _train_config(tmp_path / "run", updates,
                           compute_dtype="bfloat16")
    driver.train(config)
    _assert_learned(_episode_returns(tmp_path / "run"),
                    BANDIT_RANDOM, updates)


@pytest.mark.slow
def test_ingraph_driver_learns_bandit(tmp_path):
    """The fused in-graph backend learns the same level through the
    same driver entry point (--train_backend=ingraph)."""
    from scalable_agent_tpu import driver

    updates = 250
    config = _train_config(tmp_path / "run", updates,
                           train_backend="ingraph")
    driver.train(config)
    _assert_learned(_episode_returns(tmp_path / "run"),
                    BANDIT_RANDOM, updates)


# -- controls: the assertions above can actually fail -----------------------


def _ingraph_harness(episode_length, reward_mode, updates, batch=32):
    """A minimal real-Learner/real-agent ingraph training run returning
    the final logged episode_return."""
    import jax
    import numpy as np

    from scalable_agent_tpu.envs.device import DeviceFakeEnv
    from scalable_agent_tpu.models import ImpalaAgent
    from scalable_agent_tpu.parallel import MeshSpec, make_mesh
    from scalable_agent_tpu.runtime import Learner, LearnerHyperparams
    from scalable_agent_tpu.runtime.ingraph import InGraphTrainer

    t = 16
    env = DeviceFakeEnv(height=16, width=16, num_actions=4,
                        episode_length=episode_length,
                        reward_mode=reward_mode)
    agent = ImpalaAgent(num_actions=4)
    mesh = make_mesh(MeshSpec(data=1, model=1), devices=jax.devices()[:1])
    hp = LearnerHyperparams(
        total_environment_frames=float(updates * t * batch),
        learning_rate=0.002, entropy_cost=0.003)
    learner = Learner(agent, hp, mesh, frames_per_update=t * batch)
    trainer = InGraphTrainer(agent, learner, env, t, batch, seed=3)
    state, carry = trainer.init(jax.random.key(0))
    # Mean of the last few per-update returns (single-update windows are
    # noisy: only episodes finishing inside the unroll count).
    tail = []
    for u in range(updates):
        state, carry, metrics = trainer.train_step(state, carry,
                                                   np.int32(u))
        if u >= updates - 5:
            tail.append(float(np.asarray(metrics["episode_return"])))
    return float(np.mean(tail))


@pytest.mark.slow
def test_sign_flipped_advantage_unlearns(monkeypatch):
    """Negating the PG advantage must drive return BELOW the random
    floor — proof the learning tests catch a sign flip, the classic
    silent RL bug."""
    from scalable_agent_tpu.ops import losses as losses_lib

    orig = losses_lib.compute_policy_gradient_loss

    def flipped(logits, actions, advantages, dist_spec=None):
        return orig(logits, actions, -advantages, dist_spec=dist_spec)

    monkeypatch.setattr(
        losses_lib, "compute_policy_gradient_loss", flipped)
    final = _ingraph_harness(16, "bandit", updates=120)
    assert final < 0.75 * BANDIT_RANDOM, (
        f"sign-flipped advantage still returned {final:.2f} — the "
        f"learning assertions would not catch this bug")


@pytest.mark.slow
def test_memory_task_needs_done_reset(monkeypatch):
    """fake_memory (cue only in the first frame) trains through the
    LSTM's done-reset.  With the reset broken — carry never zeroed at
    episode boundaries — learning stalls far below the healthy run at
    the same update count.  Guards the core's reset semantics
    end-to-end (reference resets per step via tf.where(done),
    experiment.py:230-234)."""
    import flax.linen as nn

    import scalable_agent_tpu.models.agent as agent_mod

    updates = 350
    healthy = _ingraph_harness(8, "memory", updates)
    assert healthy >= 3.0 * MEMORY_RANDOM, (
        f"healthy memory run only reached {healthy:.2f}")

    class BrokenResetCoreStep(nn.Module):
        features: int

        @nn.compact
        def __call__(self, carry, xs):
            torso_out, _ = xs  # done ignored: carry never zeroed
            new_carry, y = nn.OptimizedLSTMCell(
                self.features, name="lstm")(carry, torso_out)
            return new_carry, y

    monkeypatch.setattr(agent_mod, "_CoreStep", BrokenResetCoreStep)
    broken = _ingraph_harness(8, "memory", updates)
    assert broken <= healthy - MEMORY_RANDOM, (
        f"breaking the done-reset did not hurt the memory task "
        f"(healthy {healthy:.2f}, broken {broken:.2f}) — the test has "
        f"no discriminating power")
