"""Fused single-forward loss (ISSUE 18): structure and identity.

The learner's ``_forward`` docstring promises ONE unroll produces both
the behaviour-comparison quantities and the loss's differentiated
outputs; this file pins that structurally (the lowered gradient program
contains exactly one unfused-unroll's-worth fewer convolutions than the
``fused_forward=False`` reference) and numerically (the two programs
are value-identical, because vtrace stop-gradients every comparison
input internally — the fusion is a pure program transformation, not an
algorithm change).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from __graft_entry__ import _example_trajectory
from scalable_agent_tpu.models import ImpalaAgent
from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.runtime import Learner, LearnerHyperparams

T, B, HW, NUM_ACTIONS = 6, 4, 16, 5


def _make(fused, loss="vtrace", **agent_kwargs):
    agent = ImpalaAgent(num_actions=NUM_ACTIONS, **agent_kwargs)
    mesh = make_mesh(MeshSpec(data=1, model=1),
                     devices=jax.devices()[:1])
    learner = Learner(agent, LearnerHyperparams(), mesh,
                      frames_per_update=T * B, loss=loss,
                      fused_forward=fused)
    traj = _example_trajectory(T, B, HW, HW, NUM_ACTIONS)
    state = learner.init(jax.random.key(0), traj)
    return learner, state, learner.put_trajectory(traj)


def _conv_count(learner, state, traj):
    """Convolution-primitive count in the traced gradient program —
    each forward unroll contributes the torso's conv stack, so an extra
    comparison unroll is directly visible here."""
    jaxpr = jax.make_jaxpr(
        lambda p: jax.grad(lambda q: learner._loss(q, traj)[0])(p)
    )(state.params)
    return str(jaxpr).count("conv_general_dilated")


class TestSingleForward:
    def test_fused_lowers_fewer_convs(self):
        """The unfused program runs one extra stop-gradiented unroll
        (3 torso convs); fused must shed EXACTLY those — fewer would
        mean the loss lost a real forward, more would mean the
        comparison pass snuck back in."""
        fused, f_state, f_traj = _make(True)
        unfused, u_state, u_traj = _make(False)
        n_fused = _conv_count(fused, f_state, f_traj)
        n_unfused = _conv_count(unfused, u_state, u_traj)
        assert n_unfused - n_fused == 3, (
            f"fused {n_fused} vs unfused {n_unfused} convolutions")

    @pytest.mark.parametrize("loss", ("vtrace", "impact"))
    def test_fused_and_unfused_value_identical(self, loss):
        """vtrace stop-gradients all its outputs internally, so the
        fused program and the double-forward reference are the SAME
        mathematical function — loss and gradients must agree to float
        round-off, for both loss families."""
        fused, f_state, f_traj = _make(True, loss=loss)
        unfused, u_state, u_traj = _make(False, loss=loss)

        def loss_and_grads(learner, state, traj):
            # impact reads the target network; anchoring it at the
            # online params keeps the comparison self-contained.
            val, grads = jax.value_and_grad(
                lambda p: learner._loss(
                    p, traj, target_params=state.params)[0])(state.params)
            return val, grads

        f_val, f_grads = loss_and_grads(fused, f_state, f_traj)
        u_val, u_grads = loss_and_grads(unfused, u_state, u_traj)
        np.testing.assert_allclose(f_val, u_val, rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-6), f_grads, u_grads)

    @pytest.mark.parametrize("conv_backend", ("xla", "pallas"))
    def test_update_adds_no_host_sync(self, conv_backend):
        """Acceptance (ISSUE 18): the kernel-war configuration — bf16
        compute, fused forward, either conv backend — keeps the update
        free of device↔host round-trips, pinned the same way ISSUE 12
        pinned telemetry: spied materializations + a hard transfer
        guard around steady-state updates."""
        from scalable_agent_tpu.envs.device.conformance import (
            materialization_spy)

        learner, state, traj = _make(True, compute_dtype=jnp.bfloat16,
                                     conv_backend=conv_backend)
        state, _ = learner.update(state, traj)  # warm the compile
        with materialization_spy() as calls:
            with jax.transfer_guard("disallow"):
                for _ in range(3):
                    state, _ = learner.update(state, traj)
            assert calls == [], (
                f"{conv_backend} update materialized device values on "
                f"the host: {calls}")

    def test_bf16_update_keeps_f32_params_and_finite_loss(self):
        """One real update under bf16 compute: optimizer state and
        params stay f32 (the master-weights contract) and the loss is
        finite — the e2e learning proof lives in test_learning.py's
        bf16 bandit run."""
        learner, state, traj = _make(True, compute_dtype=jnp.bfloat16)
        new_state, metrics = learner.update(state, traj)
        assert np.isfinite(float(metrics["total_loss"]))
        for leaf in jax.tree_util.tree_leaves(new_state.params):
            assert leaf.dtype == jnp.float32
