"""Async trajectory transport (runtime/transport.py, ISSUE 3).

Four contracts:

1. The packed single-copy path is BIT-exact against the per-leaf path —
   every Trajectory dtype (bool ``done`` included), odd-sized leaves
   forcing 128-byte alignment padding, optional observation streams —
   on a single device and sharded over a ('data', 'model') mesh.
2. ``per_leaf`` preserves the seed placement behavior verbatim (golden:
   identical to a bare ``jax.device_put`` against the learner's
   shardings).
3. The bounded in-flight window retires metrics FIFO with exact
   per-update ``env_frames`` accounting.
4. The driver trains end-to-end with ``--transport=packed
   --inflight_updates=2``, and packed-path losses match per-leaf losses
   over a 30-update run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.parallel.mesh import batch_sharding
from scalable_agent_tpu.runtime import Learner, LearnerHyperparams
from scalable_agent_tpu.runtime.learner import (
    _TRAJ_BATCH_AXES,
    Trajectory,
)
from scalable_agent_tpu.runtime.transport import (
    InflightWindow,
    PackedSpec,
    PackedTransport,
    PerLeafTransport,
    make_transport,
)
from scalable_agent_tpu.types import (
    AgentOutput,
    AgentState,
    Observation,
    StepOutput,
    StepOutputInfo,
)


def example_trajectory(t=3, b=4, h=5, w=7, num_actions=3,
                       with_instruction=False):
    """Every Trajectory dtype, deliberately odd trailing shapes so leaf
    byte sizes are NOT multiples of 128 (alignment padding is forced
    between leaves)."""
    rng = np.random.default_rng(0)
    t1 = t + 1
    instruction = (rng.integers(0, 1000, (t1, b, 11)).astype(np.int32)
                   if with_instruction else None)
    return Trajectory(
        agent_state=AgentState(
            c=rng.standard_normal((b, 13)).astype(np.float32),
            h=rng.standard_normal((b, 13)).astype(np.float32)),
        env_outputs=StepOutput(
            reward=rng.standard_normal((t1, b)).astype(np.float32),
            info=StepOutputInfo(
                episode_return=rng.standard_normal(
                    (t1, b)).astype(np.float32),
                episode_step=rng.integers(
                    0, 99, (t1, b)).astype(np.int32)),
            done=rng.random((t1, b)) < 0.3,
            observation=Observation(
                frame=rng.integers(0, 256, (t1, b, h, w, 3),
                                   dtype=np.uint8),
                instruction=instruction)),
        agent_outputs=AgentOutput(
            action=rng.integers(0, num_actions,
                                (t1, b)).astype(np.int32),
            policy_logits=rng.standard_normal(
                (t1, b, num_actions)).astype(np.float32),
            baseline=rng.standard_normal((t1, b)).astype(np.float32)),
    )


def traj_shardings(mesh):
    return Trajectory(
        agent_state=batch_sharding(mesh, batch_axis_index=0),
        env_outputs=batch_sharding(mesh, batch_axis_index=1),
        agent_outputs=batch_sharding(mesh, batch_axis_index=1),
    )


def assert_trees_bitwise_equal(a, b):
    la = jax.tree_util.tree_leaves(a, is_leaf=lambda x: x is None)
    lb = jax.tree_util.tree_leaves(b, is_leaf=lambda x: x is None)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if x is None or y is None:
            assert x is None and y is None
            continue
        assert np.asarray(x).dtype == np.asarray(y).dtype
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestPackedRoundTrip:
    @pytest.mark.parametrize("with_instruction", [False, True])
    def test_single_device_bitwise(self, with_instruction):
        traj = example_trajectory(with_instruction=with_instruction)
        mesh = make_mesh(MeshSpec(data=1, model=1),
                         devices=jax.devices()[:1])
        shardings = traj_shardings(mesh)
        packed = PackedTransport(mesh, shardings, _TRAJ_BATCH_AXES)
        per_leaf = PerLeafTransport(mesh, shardings)
        assert_trees_bitwise_equal(packed.put(traj),
                                   per_leaf.put(traj))

    def test_every_dtype_survives(self):
        traj = example_trajectory()
        mesh = make_mesh(MeshSpec(data=1, model=1),
                         devices=jax.devices()[:1])
        out = PackedTransport(mesh, traj_shardings(mesh),
                              _TRAJ_BATCH_AXES).put(traj)
        # The trajectory exercises bool / uint8 / int32 / float32; each
        # must come back as itself (the bool 'done' leaf in particular
        # has no bitcast path and round-trips through a != 0 compare).
        assert np.asarray(out.env_outputs.done).dtype == np.bool_
        assert np.asarray(
            out.env_outputs.observation.frame).dtype == np.uint8
        assert np.asarray(
            out.env_outputs.info.episode_step).dtype == np.int32
        assert np.asarray(out.env_outputs.reward).dtype == np.float32

    def test_sharded_unpack_on_data_model_mesh(self):
        """The satellite's ('data','model') case: batch axes shard over
        data; values and leaf shardings must match the per-leaf path."""
        traj = example_trajectory(b=4)
        mesh = make_mesh(MeshSpec(data=2, model=2),
                         devices=jax.devices()[:4])
        shardings = traj_shardings(mesh)
        packed = PackedTransport(mesh, shardings, _TRAJ_BATCH_AXES)
        per_leaf = PerLeafTransport(mesh, shardings)
        a, b = packed.put(traj), per_leaf.put(traj)
        assert_trees_bitwise_equal(a, b)
        for la, lb in zip(jax.tree_util.tree_leaves(a),
                          jax.tree_util.tree_leaves(b)):
            assert la.sharding.is_equivalent_to(lb.sharding, la.ndim), (
                la.sharding, lb.sharding)

    def test_layout_is_aligned_and_dtype_segmented(self):
        traj = example_trajectory()
        spec = PackedSpec(traj, _TRAJ_BATCH_AXES, num_shards=2)
        leaf_specs = [s for s in spec.specs if s is not None]
        # 128-byte-aligned offsets, non-overlapping segments.
        for s in leaf_specs:
            assert s.offset % 128 == 0
        ordered = sorted(leaf_specs, key=lambda s: s.offset)
        for prev, nxt in zip(ordered, ordered[1:]):
            assert prev.offset + prev.nbytes <= nxt.offset
        assert spec.shard_nbytes % 128 == 0
        # Odd leaf sizes force real padding between segments.
        assert any(s.nbytes % 128 for s in leaf_specs)
        # dtype-segmented: offset order groups dtypes contiguously.
        dtypes_in_order = [s.dtype for s in ordered]
        seen = []
        for dt in dtypes_in_order:
            if not seen or seen[-1] != dt:
                assert dt not in seen, (
                    f"dtype {dt} segments are not contiguous: "
                    f"{dtypes_in_order}")
                seen.append(dt)

    def test_device_resident_leaves_skip_the_pack(self):
        """Accum-path trajectories already live on device; the packed
        transport must re-shard them (per-leaf) instead of fetching
        device memory back to the host."""
        traj = example_trajectory()
        mesh = make_mesh(MeshSpec(data=1, model=1),
                         devices=jax.devices()[:1])
        device_traj = jax.tree_util.tree_map(
            lambda x: None if x is None else jnp.asarray(x), traj,
            is_leaf=lambda x: x is None)
        packed = PackedTransport(mesh, traj_shardings(mesh),
                                 _TRAJ_BATCH_AXES)
        out = packed.put(device_traj)
        assert_trees_bitwise_equal(out, traj)
        # The pack never ran: no layout was ever built.
        assert packed._spec is None

    def test_staging_reuse_waits_on_previous_upload(self):
        """Each staging slot records its last upload so a pack reusing
        the slot can block on it (device_put may read the host buffer
        until the transfer completes): after two puts both slots carry
        a device buffer, and the third put rotates back to slot 0
        without corrupting earlier results."""
        traj = example_trajectory()
        mesh = make_mesh(MeshSpec(data=1, model=1),
                         devices=jax.devices()[:1])
        packed = PackedTransport(mesh, traj_shardings(mesh),
                                 _TRAJ_BATCH_AXES)
        first = packed.put(traj)
        packed.put(traj)
        assert all(done is not None for done in packed._upload_done)
        third = packed.put(traj)  # rotates back onto slot 0
        assert_trees_bitwise_equal(first, third)

    def test_make_transport_rejects_unknown_name(self):
        mesh = make_mesh(MeshSpec(data=1, model=1),
                         devices=jax.devices()[:1])
        with pytest.raises(ValueError, match="unknown transport"):
            make_transport("bogus", mesh, None, None)

    def test_indivisible_batch_raises(self):
        traj = example_trajectory(b=3)  # 3 does not divide 2 shards
        with pytest.raises(ValueError, match="not divisible"):
            PackedSpec(traj, _TRAJ_BATCH_AXES, num_shards=2)


class TestPerLeafGolden:
    def test_per_leaf_matches_bare_device_put(self):
        """--transport=per_leaf is the seed path bit-for-bit: identical
        to placing the trajectory directly against the learner's
        shardings."""
        traj = example_trajectory()
        mesh = make_mesh(MeshSpec(data=1, model=1),
                         devices=jax.devices()[:1])
        shardings = traj_shardings(mesh)
        ours = PerLeafTransport(mesh, shardings).put(traj)
        golden = jax.device_put(traj, shardings)
        assert_trees_bitwise_equal(ours, golden)
        for la, lb in zip(jax.tree_util.tree_leaves(ours),
                          jax.tree_util.tree_leaves(golden)):
            assert la.sharding.is_equivalent_to(lb.sharding, la.ndim)


class TestInflightWindow:
    def _metrics(self, k, frames_per_update=8):
        return {"total_loss": jnp.float32(k),
                "env_frames": jnp.float32((k + 1) * frames_per_update)}

    def test_rejects_zero_window(self):
        with pytest.raises(ValueError, match=">= 1"):
            InflightWindow(0)

    def test_lockstep_window_retires_immediately(self):
        window = InflightWindow(1)
        window.push(self._metrics(0))
        assert window.full
        out = window.retire()
        assert float(np.asarray(out["total_loss"])) == 0.0
        assert window.depth == 0

    def test_fifo_ordering_and_env_frames_exactness(self):
        """Metrics must surface in dispatch order, each carrying its own
        update's exact frame count — the driver's accounting contract."""
        fpu = 8
        window = InflightWindow(3)
        retired = []
        for k in range(7):
            window.push(self._metrics(k, fpu))
            if window.full:
                retired.append(window.retire())
        assert window.depth == 2
        last = window.drain()
        assert window.depth == 0
        retired.append(last)
        # drain() returned the NEWEST metrics; the two drained before it
        # are not returned, so the retire sequence seen by a driver is
        # updates 0..4 then (drain) 6 — strictly increasing.
        losses = [float(np.asarray(m["total_loss"])) for m in retired]
        assert losses == [0.0, 1.0, 2.0, 3.0, 4.0, 6.0]
        for m in retired:
            k = float(np.asarray(m["total_loss"]))
            assert float(np.asarray(m["env_frames"])) == (k + 1) * fpu

    def test_drain_empty_returns_none(self):
        assert InflightWindow(2).drain() is None

    def test_depth_gauge_tracks_window(self):
        from scalable_agent_tpu.obs import MetricsRegistry

        registry = MetricsRegistry()
        window = InflightWindow(4, registry=registry)
        gauge = registry.gauge("learner/inflight_depth")
        window.push(self._metrics(0))
        window.push(self._metrics(1))
        assert gauge.value == 2.0
        window.retire()
        assert gauge.value == 1.0


class TestLearnerParity:
    def test_packed_losses_match_per_leaf_over_30_updates(self):
        """Acceptance: packed-path losses match per-leaf losses to float
        tolerance over a 30-update run (inputs are bit-identical, so the
        agreement should in fact be exact — allclose keeps the test
        robust to compiler reordering)."""
        from scalable_agent_tpu.models import ImpalaAgent

        T, B = 3, 4
        traj = example_trajectory(t=T, b=B, h=12, w=12)
        agent = ImpalaAgent(num_actions=3)
        mesh = make_mesh(MeshSpec(data=1, model=1),
                         devices=jax.devices()[:1])
        hp = LearnerHyperparams(total_environment_frames=1e5)
        losses = {}
        for name in ("per_leaf", "packed"):
            learner = Learner(agent, hp, mesh, frames_per_update=T * B,
                              transport=name)
            state = learner.init(jax.random.key(0), traj)
            run = []
            for _ in range(30):
                state, metrics = learner.update(
                    state, learner.put_trajectory(traj))
                run.append(float(np.asarray(metrics["total_loss"])))
            losses[name] = run
        np.testing.assert_allclose(losses["packed"],
                                   losses["per_leaf"], rtol=1e-6)


class TestDriverIntegration:
    def test_build_training_learner_validates_flags(self):
        from scalable_agent_tpu.config import Config
        from scalable_agent_tpu.driver import build_training_learner

        with pytest.raises(ValueError, match="unknown transport"):
            build_training_learner(
                Config(transport="bogus"), agent=None)
        with pytest.raises(ValueError, match="inflight_updates"):
            build_training_learner(
                Config(inflight_updates=0), agent=None)

    def test_driver_smoke_packed_inflight2(self, tmp_path):
        """A real driver run with --transport=packed
        --inflight_updates=2 trains, counts frames exactly, and
        publishes the new transport metrics."""
        from scalable_agent_tpu.config import Config
        from scalable_agent_tpu.driver import train
        from scalable_agent_tpu.obs import get_registry

        config = Config(
            mode="train",
            logdir=str(tmp_path / "run"),
            level_name="fake_small",
            num_actors=4,
            batch_size=2,
            unroll_length=4,
            num_action_repeats=1,
            total_environment_frames=24,  # 3 updates of 8 frames
            height=16,
            width=16,
            num_env_workers_per_group=2,
            compute_dtype="float32",
            checkpoint_interval_s=1e9,
            log_interval_s=0.0,
            transport="packed",
            inflight_updates=2,
            seed=5,
        )
        metrics = train(config)
        assert metrics["env_frames"] == 24
        assert np.isfinite(metrics["total_loss"])
        snapshot = get_registry().snapshot()
        # The packed transport staged every batch...
        assert snapshot["transport/pack_s/count"] >= 3
        assert snapshot["transport/upload_s/count"] >= 3
        assert snapshot["transport/h2d_bytes_total"] > 0
        # ...and the in-flight window retired every update.
        assert snapshot["learner/retire_s/count"] >= 3
        assert snapshot["learner/inflight_depth"] == 0.0
