"""Simulator-adapter tests.

The real simulators (deepmind_lab, ale-py, vizdoom) are optional
dependencies that are absent in CI, so the adapter logic is exercised
against mock simulator modules — the part the reference never tests at
all (its env tests require the real engines, SURVEY §4).  The gymnasium
bridge runs against the real gymnasium package.
"""

import sys
import types

import numpy as np
import pytest

from scalable_agent_tpu.envs import create_env, make_impala_stream
from scalable_agent_tpu.utils.text import hash_instruction


# ---------------------------------------------------------------------------
# DMLab (mocked deepmind_lab)
# ---------------------------------------------------------------------------


class FakeLab:
    """Duck-typed deepmind_lab.Lab recording calls."""

    instances = []

    def __init__(self, level, observations, config, renderer, level_cache):
        self.level = level
        self.observation_names = observations
        self.config = config
        self.renderer = renderer
        self.level_cache = level_cache
        self.reset_seeds = []
        self.step_calls = []
        self._steps = 0
        self._episode_len = 3
        self.width = int(config["width"])
        self.height = int(config["height"])
        FakeLab.instances.append(self)

    def reset(self, seed=None):
        self.reset_seeds.append(seed)
        self._steps = 0

    def observations(self):
        obs = {"RGB_INTERLEAVED": np.full(
            (self.height, self.width, 3), self._steps, np.uint8)}
        if "INSTR" in self.observation_names:
            obs["INSTR"] = b"go to the red door"
        return obs

    def step(self, action, num_steps=1):
        assert action.dtype == np.intc
        self.step_calls.append((tuple(int(a) for a in action), num_steps))
        self._steps += 1
        return 0.5 * num_steps

    def is_running(self):
        return self._steps < self._episode_len

    def close(self):
        pass


@pytest.fixture
def fake_deepmind_lab(monkeypatch):
    module = types.ModuleType("deepmind_lab")
    module.Lab = FakeLab
    module.set_runfiles_path = lambda path: None
    monkeypatch.setitem(sys.modules, "deepmind_lab", module)
    FakeLab.instances.clear()
    yield module


class TestDmLabAdapter:
    def test_level_resolution(self, fake_deepmind_lab):
        from scalable_agent_tpu.envs.dmlab import resolve_level

        # SF spec table.
        level, cfg = resolve_level("dmlab_very_sparse")
        assert level == "contributed/dmlab30/explore_goal_locations_large"
        assert cfg == {"minGoalDistance": "10"}
        # DMLab-30 level names.
        level, _ = resolve_level("dmlab_explore_goal_locations_small")
        assert level == "contributed/dmlab30/explore_goal_locations_small"
        # Raw paths.
        level, _ = resolve_level("dmlab_contributed/dmlab30/rooms_watermaze")
        assert level == "contributed/dmlab30/rooms_watermaze"
        with pytest.raises(ValueError, match="unknown DMLab env"):
            resolve_level("dmlab_not_a_level")

    def test_env_contract(self, fake_deepmind_lab):
        env = create_env("dmlab_watermaze", width=32, height=24,
                         num_action_repeats=4, seed=7)
        lab = FakeLab.instances[-1]
        assert lab.config["width"] == "32"
        # Native repeats declared so the stream won't double-wrap.
        assert env.native_action_repeats == 4
        obs = env.reset()
        assert obs.frame.shape == (24, 32, 3)
        # Instruction hashed host-side to fixed int32 ids.
        np.testing.assert_array_equal(
            obs.instruction, hash_instruction("go to the red door"))
        # Seeded reset chain is reproducible for equal env seeds.
        env2 = create_env("dmlab_watermaze", width=32, height=24,
                          num_action_repeats=4, seed=7)
        env2.reset()
        assert FakeLab.instances[-1].reset_seeds == lab.reset_seeds

        obs, reward, done, info = env.step(1)
        assert lab.step_calls[-1] == ((0, 0, 0, -1, 0, 0, 0), 4)  # Backward
        assert reward == 2.0 and not done and info["num_frames"] == 4
        env.step(0)
        obs, reward, done, _ = env.step(0)
        assert done
        # Terminal obs is the zero frame (episode has no observations).
        assert obs.frame.sum() == 0
        env.close(), env2.close()

    def test_stream_does_not_double_wrap(self, fake_deepmind_lab):
        stream = make_impala_stream("dmlab_watermaze", seed=3,
                                    num_action_repeats=4, width=16,
                                    height=16)
        stream.initial()
        lab = FakeLab.instances[-1]
        stream.step(0)
        # Exactly ONE Lab.step per agent step, carrying num_steps=4.
        assert len(lab.step_calls) == 1
        assert lab.step_calls[0][1] == 4
        stream.close()

    def test_level_cache_roundtrip(self, tmp_path):
        from scalable_agent_tpu.envs.dmlab import LevelCache

        cache = LevelCache(str(tmp_path / "cache"))
        src = tmp_path / "compiled.pk3"
        src.write_bytes(b"level-bytes")
        assert not cache.fetch("key1", str(tmp_path / "out.pk3"))
        cache.write("key1", str(src))
        out = tmp_path / "out.pk3"
        assert cache.fetch("key1", str(out))
        assert out.read_bytes() == b"level-bytes"


# ---------------------------------------------------------------------------
# Atari (mocked ALE behind gymnasium.make)
# ---------------------------------------------------------------------------


class FakeALE:
    """Duck-typed gymnasium NoFrameskip Atari env."""

    def __init__(self):
        import gymnasium

        self.observation_space = gymnasium.spaces.Box(
            0, 255, (210, 160, 3), np.uint8)
        self.action_space = gymnasium.spaces.Discrete(4)
        self.steps = 0

    def _obs(self):
        return np.full((210, 160, 3), self.steps % 256, np.uint8)

    def reset(self, seed=None, options=None):
        self.steps = 0
        return self._obs(), {}

    def step(self, action):
        self.steps += 1
        return self._obs(), 1.0, False, False, {}

    def close(self):
        pass


class TestAtariAdapter:
    @pytest.fixture
    def fake_gym_make(self, monkeypatch):
        import gymnasium

        made = []

        def fake_make(env_id, **kwargs):
            made.append((env_id, kwargs))
            return FakeALE()

        monkeypatch.setattr(gymnasium, "make", fake_make)
        return made

    def test_pipeline(self, fake_gym_make):
        env = create_env("atari_breakout", num_action_repeats=4)
        assert fake_gym_make[0][0] == "BreakoutNoFrameskip-v4"
        # resize 84x84 grayscale, skip 4 + stack 4 -> [84, 84, 4] HWC.
        assert env.observation_spec.frame.shape == (84, 84, 4)
        assert env.native_action_repeats == 4
        assert env.action_space.n == 4
        obs = env.reset()
        assert obs.frame.shape == (84, 84, 4)
        obs, reward, done, _ = env.step(0)
        assert reward == 4.0  # summed over the 4 skipped frames
        env.close()

    def test_unknown_game(self, fake_gym_make):
        with pytest.raises(ValueError, match="unknown Atari env"):
            create_env("atari_notagame")

    def test_montezuma_timeout_wrapped(self, fake_gym_make):
        from scalable_agent_tpu.envs.wrappers import TimeLimitWrapper

        env = create_env("atari_montezuma", num_action_repeats=4)
        layer = env
        seen_limit = None
        while hasattr(layer, "env"):
            if isinstance(layer, TimeLimitWrapper):
                seen_limit = layer._limit
            layer = layer.env
        assert seen_limit == 18000
        env.close()


# ---------------------------------------------------------------------------
# Gymnasium bridge (real gymnasium, rendered frames)
# ---------------------------------------------------------------------------


class TestGymnasiumBridge:
    def test_cartpole_rendered_frames(self):
        try:
            env = create_env("gym_CartPole-v1", height=72, width=96)
        except Exception as exc:  # headless render not available
            pytest.skip(f"gymnasium render unavailable: {exc}")
        assert env.observation_spec.frame.shape == (72, 96, 3)
        env.seed(5)
        obs = env.reset()
        assert obs.frame.shape == (72, 96, 3)
        assert obs.frame.dtype == np.uint8
        obs, reward, done, _ = env.step(0)
        assert reward == 1.0
        env.close()

    def test_full_stream_with_repeats(self):
        try:
            stream = make_impala_stream(
                "gym_CartPole-v1", seed=2, num_action_repeats=2,
                height=32, width=32)
        except Exception as exc:
            pytest.skip(f"gymnasium render unavailable: {exc}")
        out = stream.initial()
        assert out.done and out.observation.frame.shape == (32, 32, 3)
        out = stream.step(1)
        assert out.info.episode_step == 1
        stream.close()
