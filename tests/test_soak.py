"""ISSUE 20: the chaos soak engine (runtime/soak.py).

Three layers:

- **schedule sampling**: seeded determinism, warmup/cooldown bounds,
  fleet-only point gating, weight handling, unknown-point rejection.
- **invariant checker units**: the pure ``check_invariants`` against
  synthetic streams — throughput-floor breach, tainted-window
  exclusion, warmup exclusion, MTTR breach, frame mismatch, missing
  final checkpoint, stray-vs-windowed anomalies, the sentinel trip
  budget.
- **the engine end to end**: a tier-1 deterministic mini-soak — a
  REAL single-process driver soaked through the runtime channel with
  a seeded schedule spanning >= 3 distinct chaos points, asserting a
  complete graded ``soak_report.json`` — and a slow ``multiproc``
  3-process soak through the real elastic supervisor where a
  proc-targeted ``peer_exit`` forces a reshard mid-soak.
"""

import json
import os
import sys

import pytest

from scalable_agent_tpu.config import Config
from scalable_agent_tpu.runtime import soak
from scalable_agent_tpu.runtime.faults import CHAOS_POINTS, CHANNEL_NAME

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# Schedule sampling
# ---------------------------------------------------------------------------


class TestSampleSchedule:
    def test_deterministic_in_seed(self):
        a = soak.sample_schedule(7, 6, 120.0)
        b = soak.sample_schedule(7, 6, 120.0)
        assert a == b
        assert a != soak.sample_schedule(8, 6, 120.0)

    def test_events_land_in_the_middle_of_the_budget(self):
        events = soak.sample_schedule(1, 50, 100.0)
        lo = 100.0 * soak.SCHEDULE_WARMUP_FRAC
        hi = 100.0 * (1.0 - soak.SCHEDULE_COOLDOWN_FRAC)
        assert all(lo <= e["t_s"] <= hi for e in events)
        assert [e["t_s"] for e in events] == sorted(
            e["t_s"] for e in events)

    def test_single_process_excludes_fleet_only_points(self):
        events = soak.sample_schedule(2, 200, 100.0, num_processes=1)
        points = {e["point"] for e in events}
        assert points and not (points & set(soak.FLEET_ONLY_POINTS))
        assert all(e["proc"] is None for e in events)

    def test_fleet_schedule_targets_sampled_processes(self):
        events = soak.sample_schedule(2, 50, 100.0, num_processes=3)
        assert all(e["proc"] in (0, 1, 2) for e in events)
        assert {e["point"] for e in events} & {"peer_exit"}

    def test_zero_weight_points_are_never_sampled(self):
        events = soak.sample_schedule(3, 300, 100.0, num_processes=3)
        assert "preempt_sigterm" not in {e["point"] for e in events}

    def test_unknown_point_raises(self):
        with pytest.raises(ValueError, match="bogus"):
            soak.sample_schedule(0, 1, 10.0, points=["bogus"])

    def test_every_default_weight_key_is_a_registry_point(self):
        assert set(soak.DEFAULT_WEIGHTS) == set(CHAOS_POINTS)
        assert set(soak.DEFAULT_RECOVERY_S) == set(CHAOS_POINTS)

    def test_recovery_window_rides_the_event(self):
        events = soak.sample_schedule(
            4, 10, 100.0, points=["nan_grad"],
            recovery_s={"nan_grad": 7.5})
        assert all(e["recovery_s"] == 7.5 for e in events)


# ---------------------------------------------------------------------------
# Invariant checker units (pure, synthetic streams)
# ---------------------------------------------------------------------------


def _rows(fps_list, t0=1000.0, dt=2.0):
    return [{"step": i, "time": t0 + dt * i, "fps": fps}
            for i, fps in enumerate(fps_list)]


def _good_ckpt(step=9, fpu=32):
    return {"verified": True, "step": step,
            "env_frames": float(step * fpu)}


class TestCheckInvariants:
    def test_healthy_run_passes_everything(self):
        inv = soak.check_invariants(
            metrics_rows=_rows([100.0] * 10),
            mttr_events=[{"mttr_s": 12.0}],
            anomalies=[],
            injected=[],
            ckpt=_good_ckpt(),
            frames_per_update=32,
            mttr_ceiling_s=30.0)
        assert all(v["ok"] for v in inv.values()), inv
        assert set(inv) == {
            "throughput_floor", "mttr_ceiling", "frame_exactness",
            "final_checkpoint", "quiet_outside_windows"}

    def test_floor_breach_outside_windows_fails(self):
        fps = [100.0] * 10
        fps[7] = 10.0  # healthy-window sag: row at t0+14, no window
        inv = soak.check_invariants(
            metrics_rows=_rows(fps), mttr_events=[], anomalies=[],
            injected=[], ckpt=_good_ckpt(), frames_per_update=32)
        verdict = inv["throughput_floor"]
        assert not verdict["ok"]
        assert verdict["worst_frac"] < 0.8
        assert verdict["baseline_fps"] == 100.0

    def test_sag_inside_a_declared_window_is_excluded(self):
        fps = [100.0] * 10
        fps[5] = 10.0  # row at t0+10s, interval (t0+8, t0+10)
        injected = [{"point": "worker_kill", "t_unix": 1000.0 + 8.5,
                     "recovery_s": 3.0}]
        inv = soak.check_invariants(
            metrics_rows=_rows(fps), mttr_events=[], anomalies=[],
            injected=injected, ckpt=_good_ckpt(),
            frames_per_update=32)
        verdict = inv["throughput_floor"]
        assert verdict["ok"], verdict
        # startup row + the tainted rows around the window
        assert verdict["rows_excluded"] >= 2

    def test_warmup_rows_are_excluded(self):
        fps = [5.0, 20.0, 100.0, 100.0, 100.0, 100.0]  # compile ramp
        inv = soak.check_invariants(
            metrics_rows=_rows(fps), mttr_events=[], anomalies=[],
            injected=[], ckpt=_good_ckpt(), frames_per_update=32,
            warmup_until_unix=1000.0 + 4.5)
        assert inv["throughput_floor"]["ok"]
        # rows whose interval STARTS before the warmup cutoff are out:
        # only intervals (1006,1008) and (1008,1010) survive
        assert inv["throughput_floor"]["rows_graded"] == 2

    def test_no_healthy_rows_is_an_explicit_fail(self):
        inv = soak.check_invariants(
            metrics_rows=[], mttr_events=[], anomalies=[],
            injected=[], ckpt=_good_ckpt(), frames_per_update=32)
        assert not inv["throughput_floor"]["ok"]
        assert "no healthy-window" in inv["throughput_floor"]["detail"]

    def test_mttr_breach_fails_and_vacuous_passes(self):
        breach = soak.check_invariants(
            metrics_rows=_rows([100.0] * 4),
            mttr_events=[{"mttr_s": 45.0}, {"mttr_s": 200.0}],
            anomalies=[], injected=[], ckpt=_good_ckpt(),
            frames_per_update=32, mttr_ceiling_s=180.0)
        assert not breach["mttr_ceiling"]["ok"]
        assert breach["mttr_ceiling"]["worst_s"] == 200.0
        vacuous = soak.check_invariants(
            metrics_rows=_rows([100.0] * 4), mttr_events=[],
            anomalies=[], injected=[], ckpt=_good_ckpt(),
            frames_per_update=32)
        assert vacuous["mttr_ceiling"]["ok"]
        assert vacuous["mttr_ceiling"]["events"] == 0

    def test_frame_mismatch_fails(self):
        ckpt = {"verified": True, "step": 9, "env_frames": 289.0}
        inv = soak.check_invariants(
            metrics_rows=_rows([100.0] * 4), mttr_events=[],
            anomalies=[], injected=[], ckpt=ckpt,
            frames_per_update=32)  # expected 288
        assert not inv["frame_exactness"]["ok"]
        assert inv["frame_exactness"]["expected"] == 288.0

    def test_missing_checkpoint_fails_both_ckpt_invariants(self):
        inv = soak.check_invariants(
            metrics_rows=_rows([100.0] * 4), mttr_events=[],
            anomalies=[],
            injected=[],
            ckpt={"verified": False, "step": None, "env_frames": None,
                  "error": "no checkpoint on disk"},
            frames_per_update=32)
        assert not inv["final_checkpoint"]["ok"]
        assert not inv["frame_exactness"]["ok"]

    def test_stray_anomaly_fails_windowed_anomaly_passes(self):
        injected = [{"point": "actor_raise", "t_unix": 2000.0,
                     "recovery_s": 20.0}]
        windowed = soak.check_invariants(
            metrics_rows=_rows([100.0] * 4), mttr_events=[],
            anomalies=[{"id": "a001-x", "ts_unix": 2010.0}],
            injected=injected, ckpt=_good_ckpt(),
            frames_per_update=32)
        assert windowed["quiet_outside_windows"]["ok"]
        stray = soak.check_invariants(
            metrics_rows=_rows([100.0] * 4), mttr_events=[],
            anomalies=[{"id": "a001-x", "ts_unix": 2100.0,
                        "detector": "throughput"}],
            injected=injected, ckpt=_good_ckpt(),
            frames_per_update=32)
        verdict = stray["quiet_outside_windows"]
        assert not verdict["ok"]
        assert verdict["stray_anomalies"][0]["id"] == "a001-x"

    def test_sentinel_trips_beyond_the_injected_budget_fail(self):
        injected = [{"point": "param_bitflip", "t_unix": 2000.0,
                     "recovery_s": 30.0}]
        within = soak.check_invariants(
            metrics_rows=_rows([100.0] * 4), mttr_events=[],
            anomalies=[], injected=injected, ckpt=_good_ckpt(),
            frames_per_update=32, sentinel_trips=1)
        assert within["quiet_outside_windows"]["ok"]
        beyond = soak.check_invariants(
            metrics_rows=_rows([100.0] * 4), mttr_events=[],
            anomalies=[], injected=injected, ckpt=_good_ckpt(),
            frames_per_update=32, sentinel_trips=2)
        assert not beyond["quiet_outside_windows"]["ok"]

    def test_never_injected_events_declare_no_window(self):
        planned_only = [{"point": "worker_kill",
                         "recovery_s": 1000.0}]  # no t_unix
        inv = soak.check_invariants(
            metrics_rows=_rows([100.0] * 6), mttr_events=[],
            anomalies=[{"id": "a001-x", "ts_unix": 1004.0}],
            injected=planned_only, ckpt=_good_ckpt(),
            frames_per_update=32)
        assert not inv["quiet_outside_windows"]["ok"]


# ---------------------------------------------------------------------------
# Report I/O
# ---------------------------------------------------------------------------


class TestReportIO:
    def test_atomic_write_then_read_roundtrip(self, tmp_path):
        report = {"schema_version": soak.SOAK_SCHEMA_VERSION,
                  "pass": True, "invariants": {}}
        path = soak.write_report(str(tmp_path), report)
        assert os.path.basename(path) == soak.SOAK_REPORT_NAME
        assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]
        assert soak.read_soak_report(str(tmp_path)) == report

    def test_unreadable_report_reads_as_none(self, tmp_path):
        assert soak.read_soak_report(str(tmp_path)) is None
        (tmp_path / soak.SOAK_REPORT_NAME).write_text("{torn")
        assert soak.read_soak_report(str(tmp_path)) is None


# ---------------------------------------------------------------------------
# The engine, end to end
# ---------------------------------------------------------------------------

_INVARIANT_NAMES = {"throughput_floor", "mttr_ceiling",
                    "frame_exactness", "final_checkpoint",
                    "quiet_outside_windows"}


def _soak_config(tmp_path, **overrides):
    defaults = dict(
        mode="train",
        logdir=str(tmp_path / "run"),
        level_name="fake_small",
        num_actors=4,
        batch_size=2,
        unroll_length=4,
        num_action_repeats=1,
        total_environment_frames=10_000_000,  # budget ends the run
        height=16,
        width=16,
        num_env_workers_per_group=2,
        compute_dtype="float32",
        checkpoint_interval_s=1.0,
        log_interval_s=0.25,
        preemption_grace_s=30.0,
        seed=5,
    )
    defaults.update(overrides)
    return Config(**defaults)


class TestMiniSoak:
    """Tier-1 acceptance: a real seeded single-process soak, >= 3
    distinct chaos points through the runtime channel, one complete
    graded report.  ~40s wall: one driver subprocess for the whole
    class."""

    SEED = 1  # schedule spans 4 distinct single-process points

    @pytest.fixture(scope="class")
    def report_and_logdir(self, tmp_path_factory):
        tmp_path = tmp_path_factory.mktemp("mini_soak")
        config = _soak_config(tmp_path)
        report = soak.run_soak(
            config, seed=self.SEED, num_faults=5, budget_s=25.0,
            drain_grace_s=90.0, env={"JAX_PLATFORMS": "cpu"})
        return report, config.logdir

    def test_schedule_spans_three_distinct_points(self):
        events = soak.sample_schedule(self.SEED, 5, 25.0)
        assert len({e["point"] for e in events}) >= 3

    def test_report_is_complete_and_graded(self, report_and_logdir):
        report, logdir = report_and_logdir
        assert report["schema_version"] == soak.SOAK_SCHEMA_VERSION
        assert set(report["invariants"]) == _INVARIANT_NAMES
        assert all(isinstance(v["ok"], bool)
                   for v in report["invariants"].values())
        assert isinstance(report["pass"], bool)
        # the written artifact is the returned report
        assert soak.read_soak_report(logdir) == report

    def test_at_least_three_distinct_points_injected(
            self, report_and_logdir):
        report, logdir = report_and_logdir
        assert len(report["injected"]) >= 3
        assert len(report["points"]) >= 3
        # and the channel file shows exactly the injected lines
        lines = open(os.path.join(logdir, CHANNEL_NAME)).read(
        ).splitlines()
        assert len(lines) == len(report["injected"])

    def test_faults_actually_landed_in_the_worker(
            self, report_and_logdir):
        report, _ = report_and_logdir
        assert report["counters"]["faults_injected_total"] >= 3

    def test_injected_events_are_not_reported_as_skipped(
            self, report_and_logdir):
        # Regression: run_soak used to stamp t_unix on a COPY of the
        # schedule entry, so grade_soak (which tells the two apart by
        # the missing t_unix) reported every injected event under
        # planned_not_injected too.
        report, _ = report_and_logdir
        injected = {(e["point"], e["t_s"]) for e in report["injected"]}
        skipped = {(e["point"], e["t_s"])
                   for e in report["planned_not_injected"]}
        assert injected, "the mini soak injected nothing"
        assert not injected & skipped
        assert all(e.get("t_unix") for e in report["injected"])

    def test_drain_left_exact_frames_and_a_verified_checkpoint(
            self, report_and_logdir):
        report, _ = report_and_logdir
        assert report["worker_rc"] == 0
        assert report["drained"] is True
        assert report["invariants"]["final_checkpoint"]["ok"]
        assert report["invariants"]["frame_exactness"]["ok"]

    def test_cli_report_renders_the_verdict(self, report_and_logdir,
                                            capsys):
        report, logdir = report_and_logdir
        rc = soak.main(["report", f"--logdir={logdir}"])
        out = capsys.readouterr().out
        assert "chaos soak:" in out
        for name in _INVARIANT_NAMES:
            assert name in out
        assert rc == (0 if report["pass"] else 1)


@pytest.mark.slow
@pytest.mark.multiproc
class TestFleetSoak:
    """The acceptance soak: a 3-process elastic fleet, >= 3 injected
    faults across >= 3 distinct points, a proc-targeted ``peer_exit``
    forcing a real mid-soak reshard, one complete graded report."""

    SEED = 35  # peer_exit@39s on proc 1, then actor_raise + nan_grads
    POINTS = ("peer_exit", "nan_grad", "throughput_sag", "actor_raise")

    def test_three_process_soak_reshards_and_grades(self, tmp_path):
        fakes = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "fakes")
        sys.path.insert(0, fakes)
        try:
            import multiproc
        finally:
            sys.path.remove(fakes)
        config = _soak_config(
            tmp_path, num_actors=3, batch_size=6, unroll_length=3,
            num_env_workers_per_group=1, seed=3,
            checkpoint_interval_s=1.0, log_interval_s=0.2,
            peer_timeout_s=6.0, preemption_grace_s=45.0,
            distributed_num_processes=3,
            elastic_rejoin_delay_s=1_000_000.0,
            elastic_restart_budget=4)
        schedule = soak.sample_schedule(
            self.SEED, 5, 120.0, points=list(self.POINTS),
            weights={"peer_exit": 3.0}, num_processes=3)
        peer_exits = [e for e in schedule if e["point"] == "peer_exit"]
        assert len(peer_exits) == 1 and peer_exits[0]["proc"] == 1
        assert len({e["point"] for e in schedule}) >= 3

        report = soak.run_soak(
            config, seed=self.SEED, num_faults=5, budget_s=120.0,
            points=list(self.POINTS), weights={"peer_exit": 3.0},
            drain_grace_s=150.0,
            env=multiproc.base_env(devices_per_process=1))

        assert set(report["invariants"]) == _INVARIANT_NAMES
        assert len(report["injected"]) >= 3
        assert len(report["points"]) >= 3
        assert report["invariants"]["final_checkpoint"]["ok"]
        assert report["invariants"]["frame_exactness"]["ok"]
        # the peer_exit produced a real reshard under the supervisor
        events = [json.loads(line) for line in open(os.path.join(
            config.logdir, "fleet_epochs.jsonl")).read().splitlines()
            if line]
        launches = [e for e in events if e.get("event") == "launch"]
        assert any(e.get("epoch", 0) >= 1 for e in launches), (
            "peer_exit never forced a reshard")
        exits = [e for e in events if e.get("event") == "exit"
                 and e.get("epoch") == 0]
        assert exits and exits[0].get("outcome") == "reshard"
