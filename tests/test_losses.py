"""Loss-term tests against literal numpy formulations.

(reference loss definitions: experiment.py:324-343,377-382)
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from scalable_agent_tpu.ops import losses


def _softmax(x, axis=-1):
    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


def test_baseline_loss():
    adv = np.array([[1.0, -2.0], [3.0, 0.5]], np.float32)
    expected = 0.5 * np.sum(adv ** 2)
    np.testing.assert_allclose(
        expected, float(losses.compute_baseline_loss(adv)), rtol=1e-6)


def test_entropy_loss():
    rng = np.random.RandomState(0)
    logits = rng.normal(size=(4, 3, 6)).astype(np.float32)
    p = _softmax(logits)
    entropy = -np.sum(p * np.log(p), axis=-1)
    expected = -np.sum(entropy)
    np.testing.assert_allclose(
        expected, float(losses.compute_entropy_loss(logits)),
        rtol=1e-4, atol=1e-4)


def test_policy_gradient_loss():
    rng = np.random.RandomState(1)
    logits = rng.normal(size=(5, 2, 4)).astype(np.float32)
    actions = rng.randint(0, 4, (5, 2)).astype(np.int32)
    adv = rng.normal(size=(5, 2)).astype(np.float32)

    p = _softmax(logits)
    ce = -np.log(np.take_along_axis(p, actions[..., None], -1)[..., 0])
    expected = np.sum(ce * adv)
    np.testing.assert_allclose(
        expected,
        float(losses.compute_policy_gradient_loss(logits, actions, adv)),
        rtol=1e-4, atol=1e-4)


def test_policy_gradient_loss_stops_advantage_grad():
    """Gradient must flow through logits only, not advantages."""
    logits = jnp.ones((3, 2, 4))
    actions = jnp.zeros((3, 2), jnp.int32)

    def f(adv):
        return losses.compute_policy_gradient_loss(logits, actions, adv)

    g = jax.grad(f)(jnp.ones((3, 2)))
    np.testing.assert_allclose(np.zeros((3, 2)), np.asarray(g))


def test_clip_rewards_abs_one():
    r = np.array([-5.0, -0.5, 0.0, 0.7, 9.0], np.float32)
    np.testing.assert_allclose(
        np.clip(r, -1, 1), np.asarray(losses.clip_rewards(r, "abs_one")))


def test_clip_rewards_soft_asymmetric():
    r = np.array([-10.0, -1.0, 0.0, 1.0, 10.0], np.float32)
    squeezed = np.tanh(r / 5.0)
    expected = np.where(r < 0, 0.3 * squeezed, squeezed) * 5.0
    np.testing.assert_allclose(
        expected, np.asarray(losses.clip_rewards(r, "soft_asymmetric")),
        rtol=1e-4)
    # Asymmetry: negatives shrunk harder than positives.
    out = np.asarray(losses.clip_rewards(r, "soft_asymmetric"))
    assert abs(out[0]) < abs(out[-1])


def test_clip_rewards_none_and_unknown():
    r = np.array([3.0], np.float32)
    np.testing.assert_allclose(r, np.asarray(losses.clip_rewards(r, "none")))
    with pytest.raises(ValueError):
        losses.clip_rewards(r, "bogus")
