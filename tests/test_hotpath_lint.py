"""Static guard: no host callbacks inside the jitted hot path.

"Telemetry never syncs the host" (obs/device_telemetry.py) has a
dynamic proof — tests/test_device_telemetry.py counts transfers around
a telemetry-bearing update — and this is its static complement: the
modules that build jitted programs (``scalable_agent_tpu/runtime/`` and
``scalable_agent_tpu/models/``) must not call the jax escape hatches
that smuggle a host round-trip into a compiled program:

- ``jax.debug.print`` / ``jax.debug.callback`` — per-executed-trace
  host callbacks,
- ``jax.pure_callback`` / ``jax.experimental.io_callback`` /
  ``host_callback`` — host calls inside the program.

Any of these inside the update/rollout/fused-step path would reopen
the per-step host↔device chatter the whole architecture exists to
close (and on the fused flywheel there is no "slow path" to hide them
on).  The lint walks the ASTs (the ``test_collective_lint.py`` /
``test_ledger_lint.py`` pattern); a justified exception goes in
``ALLOWLIST`` with the module-relative path and callee name — and a
stale entry FAILS, so the list can only shrink.
"""

import ast
import os

import scalable_agent_tpu

PKG_DIR = os.path.dirname(os.path.abspath(scalable_agent_tpu.__file__))

# Directories whose modules assemble jitted programs.  envs/device is
# the on-device environment package (ISSUE 15): a debug print or
# callback in an env step path would ride INSIDE the fused megastep's
# scan — per-step host chatter at rollout frequency, the worst spot of
# all.  ops holds the Pallas kernels (ISSUE 18) — a callback there
# would sit inside the innermost MXU loop of every update.
HOT_DIRS = ("runtime", "models", "ops", os.path.join("envs", "device"))

# Callee names that are host callbacks regardless of how they are
# reached (bare name, jax.pure_callback, jax.experimental.io_callback,
# from-imports, ...).
FORBIDDEN_NAMES = frozenset((
    "io_callback",
    "pure_callback",
    "host_callback",
    "call_tbx",  # host_callback's legacy entry points
))

# (relative_path, callee) -> justification.  Empty on purpose: nothing
# in the hot path needs a host callback today.  A future entry must
# say WHY the callback cannot ride device telemetry instead.
ALLOWLIST = {}


def _callee_chain(func) -> str:
    """Dotted name of a call target, best effort: ``jax.debug.print``
    -> "jax.debug.print", bare ``io_callback`` -> "io_callback"."""
    parts = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_forbidden(chain: str) -> bool:
    if not chain:
        return False
    leaf = chain.split(".")[-1]
    if leaf in FORBIDDEN_NAMES:
        return True
    # jax.debug.print / jax.debug.callback (but NOT logging-style
    # .print on arbitrary objects without the debug parent, and not
    # the flight recorder's own .callback attributes).
    if leaf in ("print", "callback"):
        pieces = chain.split(".")
        return len(pieces) >= 2 and pieces[-2] == "debug"
    return False


def _scan_module(path: str):
    tree = ast.parse(open(path).read(), filename=path)
    hits = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            chain = _callee_chain(node.func)
            if _is_forbidden(chain):
                hits.append((chain, node.lineno))
        # from jax.experimental import io_callback  (importing it into
        # a hot module is the lint's business even before it is called)
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names or ():
                if alias.name in FORBIDDEN_NAMES:
                    hits.append((alias.name, node.lineno))
    return hits


def _hot_modules():
    for sub in HOT_DIRS:
        base = os.path.join(PKG_DIR, sub)
        for dirpath, _, filenames in os.walk(base):
            for filename in sorted(filenames):
                if filename.endswith(".py"):
                    yield os.path.join(dirpath, filename)


def test_no_host_callbacks_in_jitted_hot_path():
    violations = []
    used_allowlist = set()
    for path in _hot_modules():
        rel = os.path.relpath(path, PKG_DIR)
        for chain, lineno in _scan_module(path):
            key = (rel, chain)
            if key in ALLOWLIST:
                used_allowlist.add(key)
                continue
            violations.append(f"{rel}:{lineno} calls {chain}")
    assert not violations, (
        "host callbacks inside the jitted hot path (device telemetry "
        "exists so these are never needed — obs/device_telemetry.py):\n"
        + "\n".join(violations))
    stale = set(ALLOWLIST) - used_allowlist
    assert not stale, (
        f"stale hot-path allowlist entries (the call is gone — delete "
        f"them): {sorted(stale)}")


def test_lint_actually_detects_violations(tmp_path):
    """The lint must FAIL on code using the forbidden callbacks — a
    matcher that never matches would pass the repo vacuously."""
    sample = tmp_path / "bad.py"
    sample.write_text(
        "import jax\n"
        "from jax.experimental import io_callback\n"
        "def f(x):\n"
        "    jax.debug.print('x={}', x)\n"
        "    jax.pure_callback(lambda v: v, x, x)\n"
        "    return x\n")
    hits = _scan_module(str(sample))
    chains = {chain for chain, _ in hits}
    assert "jax.debug.print" in chains
    assert "jax.pure_callback" in chains
    assert "io_callback" in chains  # the from-import itself


def test_hot_dirs_exist_and_are_scanned():
    modules = list(_hot_modules())
    names = {os.path.relpath(m, PKG_DIR) for m in modules}
    assert any(n.startswith("runtime") for n in names)
    assert any(n.startswith("models") for n in names)
    assert os.path.join("runtime", "learner.py") in names
    assert os.path.join("envs", "device", "gridworld.py") in names
    assert os.path.join("envs", "device", "fake.py") in names
    assert os.path.join("ops", "conv_pallas.py") in names
    assert os.path.join("ops", "lstm_pallas.py") in names


# -- registry closure: DEVICE_LEVELS <-> conformance parametrization ---------

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _conformance_levels_literal():
    """The CONFORMANCE_LEVELS tuple out of
    tests/test_device_conformance.py, read via AST (no import: the lint
    must see exactly what is WRITTEN, and stay independent of that
    module's import-time behavior)."""
    path = os.path.join(TESTS_DIR, "test_device_conformance.py")
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            if "CONFORMANCE_LEVELS" in targets:
                return tuple(ast.literal_eval(node.value))
    raise AssertionError(
        "tests/test_device_conformance.py no longer defines the "
        "CONFORMANCE_LEVELS literal the registry-closure lint reads")


def test_every_device_level_has_a_conformance_parametrization():
    """Registry closure (ISSUE 15 satellite): a level registered in
    DEVICE_LEVELS without a conformance parametrization would ship an
    unchecked world — and a stale parametrization for a deleted level
    would green-light nothing.  Both directions fail."""
    from scalable_agent_tpu.envs.device.protocol import DEVICE_LEVELS

    declared = set(_conformance_levels_literal())
    registered = set(DEVICE_LEVELS)
    missing = registered - declared
    stale = declared - registered
    assert not missing, (
        f"device levels registered without a conformance "
        f"parametrization — add them to CONFORMANCE_LEVELS in "
        f"tests/test_device_conformance.py: {sorted(missing)}")
    assert not stale, (
        f"stale CONFORMANCE_LEVELS entries (level no longer "
        f"registered — delete them): {sorted(stale)}")


# -- registry closure: CONV_BACKENDS <-> torso routing <-> driver -------------


def test_every_conv_backend_routes_through_every_torso():
    """Registry closure (ISSUE 18 satellite): every backend in
    CONV_BACKENDS must actually build through BOTH torso classes (a
    registered name a torso silently ignores would flip the stem back
    to XLA while the flag claims Pallas), the driver's auto resolution
    must land inside the registry, and an unregistered name must be
    rejected — the flag surface and the routing cannot drift apart."""
    import jax
    import jax.numpy as jnp
    import pytest

    from scalable_agent_tpu import driver
    from scalable_agent_tpu.config import Config
    from scalable_agent_tpu.models.networks import CONV_BACKENDS, TORSOS

    frame = jnp.zeros((2, 32, 32, 3), jnp.uint8)
    for backend in CONV_BACKENDS:
        for name, torso_cls in TORSOS.items():
            torso = torso_cls(conv_backend=backend)
            params = torso.init(jax.random.key(0), frame)
            out = torso.apply(params, frame)
            assert out.shape[0] == 2, (name, backend)

    config = Config(mode="train", level_name="fake_bandit",
                    logdir="/tmp/unused", conv_backend="auto")
    assert driver.resolve_conv_backend(config) in CONV_BACKENDS
    with pytest.raises(ValueError, match="conv_backend"):
        driver.resolve_conv_backend(
            Config(mode="train", level_name="fake_bandit",
                   logdir="/tmp/unused", conv_backend="winograd"))
