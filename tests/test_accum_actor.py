"""On-device trajectory accumulation (runtime/accum_actor.py).

The accum path must be a pure data-flow optimization: given identical env
seeds, params, and rng seeds it must emit byte-identical trajectories to
the structural ``VectorActor`` path — same [T+1, B] layout, same overlap
entry, same rng stream (the learner cannot tell which actor produced a
batch).  Plus an end-to-end ActorPool(inference_mode='accum') → Learner
consumption test mirroring the structural/service ones.
"""

import functools

import jax
import numpy as np
import pytest

from scalable_agent_tpu.envs import MultiEnv, make_impala_stream
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.models import ImpalaAgent
from scalable_agent_tpu.models import agent as agent_mod
from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.runtime import (
    ActorPool,
    Learner,
    LearnerHyperparams,
    Trajectory,
    VectorActor,
)
from scalable_agent_tpu.runtime.accum_actor import (
    AccumPrograms,
    AccumVectorActor,
)

NUM_ACTIONS = 5
FRAME = TensorSpec((16, 16, 3), np.uint8, "frame")
T = 6
B = 4


def make_envs(n=B, workers=2):
    fns = [functools.partial(make_impala_stream, "fake_small", seed=i,
                             num_actions=NUM_ACTIONS)
           for i in range(n)]
    return MultiEnv(fns, FRAME, num_workers=workers)


@pytest.fixture(scope="module")
def agent_and_params():
    agent = ImpalaAgent(num_actions=NUM_ACTIONS)
    envs = make_envs(1, workers=1)
    try:
        params = agent.init(
            jax.random.key(0),
            np.zeros((1, 1), np.int32),
            jax.tree_util.tree_map(
                lambda x: None if x is None else np.asarray(x)[None][:, :1],
                envs.initial(), is_leaf=lambda x: x is None),
            agent_mod.initial_state(1))
    finally:
        envs.close()
    return agent, params


def tree_as_numpy(tree):
    return jax.tree_util.tree_map(
        lambda x: None if x is None else np.asarray(x), tree,
        is_leaf=lambda x: x is None)


class TestEquivalence:
    def test_trajectories_match_structural_path(self, agent_and_params):
        agent, params = agent_and_params
        envs_a = make_envs()
        envs_b = make_envs()
        try:
            structural = VectorActor(agent, envs_a, T, seed=7)
            programs = AccumPrograms(agent, T, B, FRAME.shape)
            accum = AccumVectorActor(programs, envs_b, seed=7)
            for unroll_index in range(3):
                out_s = structural.run_unroll(params)
                out_a = accum.run_unroll(params)
                s = tree_as_numpy(out_s)
                a = tree_as_numpy(out_a)
                np.testing.assert_array_equal(
                    s.env_outputs.observation.frame,
                    a.env_outputs.observation.frame,
                    err_msg=f"frames diverge at unroll {unroll_index}")
                np.testing.assert_array_equal(
                    s.agent_outputs.action, a.agent_outputs.action)
                np.testing.assert_array_equal(
                    s.env_outputs.done, a.env_outputs.done)
                np.testing.assert_allclose(
                    s.env_outputs.reward, a.env_outputs.reward, rtol=1e-6)
                np.testing.assert_allclose(
                    s.env_outputs.info.episode_return,
                    a.env_outputs.info.episode_return, rtol=1e-6)
                np.testing.assert_array_equal(
                    s.env_outputs.info.episode_step,
                    a.env_outputs.info.episode_step)
                np.testing.assert_allclose(
                    s.agent_outputs.policy_logits,
                    a.agent_outputs.policy_logits, rtol=1e-5, atol=1e-6)
                np.testing.assert_allclose(
                    s.agent_outputs.baseline, a.agent_outputs.baseline,
                    rtol=1e-5, atol=1e-6)
                np.testing.assert_allclose(
                    s.agent_state.c, a.agent_state.c, rtol=1e-5, atol=1e-6)
                np.testing.assert_allclose(
                    s.agent_state.h, a.agent_state.h, rtol=1e-5, atol=1e-6)
        finally:
            envs_a.close()
            envs_b.close()

    def test_instruction_level_matches_structural_path(self):
        """Accum == structural on an instruction-carrying level (the
        language-DMLab shape, reference environments.py:76): instruction
        int32s ride the per-step upload into their own device buffer
        (VERDICT r3 item 6)."""
        agent = ImpalaAgent(num_actions=NUM_ACTIONS, use_instruction=True)

        def make_instr_envs():
            fns = [functools.partial(
                make_impala_stream, "fake_small", seed=i,
                num_actions=NUM_ACTIONS, with_instruction=True)
                for i in range(B)]
            return MultiEnv(fns, FRAME, num_workers=2)

        envs_a = make_instr_envs()
        envs_b = make_instr_envs()
        try:
            init_out = envs_a.initial()
            assert init_out.observation.instruction is not None
            instr_shape = init_out.observation.instruction.shape[1:]
            params = agent.init(
                jax.random.key(0),
                np.zeros((1, B), np.int32),
                jax.tree_util.tree_map(
                    lambda x: None if x is None else np.asarray(x)[None],
                    init_out, is_leaf=lambda x: x is None),
                agent_mod.initial_state(B))
            structural = VectorActor(agent, envs_a, T, seed=7)
            structural._last_env_output = init_out  # reuse the probe
            structural._core_state = agent_mod.initial_state(B)
            from scalable_agent_tpu.types import AgentOutput as AO
            structural._last_agent_output = AO(
                action=np.asarray(agent.zero_actions(B)),
                policy_logits=np.zeros((B, agent.num_logits), np.float32),
                baseline=np.zeros((B,), np.float32))
            programs = AccumPrograms(agent, T, B, FRAME.shape,
                                     instruction_shape=instr_shape)
            accum = AccumVectorActor(programs, envs_b, seed=7)
            for _ in range(2):
                s = tree_as_numpy(structural.run_unroll(params))
                a = tree_as_numpy(accum.run_unroll(params))
                np.testing.assert_array_equal(
                    s.env_outputs.observation.instruction,
                    a.env_outputs.observation.instruction)
                np.testing.assert_array_equal(
                    s.agent_outputs.action, a.agent_outputs.action)
                np.testing.assert_allclose(
                    s.agent_outputs.policy_logits,
                    a.agent_outputs.policy_logits, rtol=1e-5, atol=1e-6)
        finally:
            envs_a.close()
            envs_b.close()

    def test_mismatched_instruction_config_is_clear_error(
            self, agent_and_params):
        """An instruction-emitting env against programs built without
        instruction_shape fails with a pointed message, not a tree-map
        crash."""
        agent, params = agent_and_params
        fns = [functools.partial(
            make_impala_stream, "fake_small", seed=i,
            num_actions=NUM_ACTIONS, with_instruction=True)
            for i in range(B)]
        envs = MultiEnv(fns, FRAME, num_workers=2)
        try:
            programs = AccumPrograms(agent, T, B, FRAME.shape)
            actor = AccumVectorActor(programs, envs, seed=1)
            with pytest.raises(ValueError, match="instruction"):
                actor.run_unroll(params)
        finally:
            envs.close()

    def test_overlap_entry_carries_across_unrolls(self, agent_and_params):
        """Entry 0 of unroll k+1 == entry T of unroll k (reference
        trajectory layout, experiment.py:311-321)."""
        agent, params = agent_and_params
        envs = make_envs()
        try:
            programs = AccumPrograms(agent, T, B, FRAME.shape)
            actor = AccumVectorActor(programs, envs, seed=3)
            first = tree_as_numpy(actor.run_unroll(params))
            second = tree_as_numpy(actor.run_unroll(params))
            np.testing.assert_array_equal(
                first.env_outputs.observation.frame[T],
                second.env_outputs.observation.frame[0])
            np.testing.assert_array_equal(
                first.agent_outputs.action[T],
                second.agent_outputs.action[0])
            np.testing.assert_allclose(
                first.agent_outputs.policy_logits[T],
                second.agent_outputs.policy_logits[0])
        finally:
            envs.close()


class TestGroupedCoDispatch:
    def test_fused_matches_threaded_accum(self, agent_and_params):
        """GroupedAccumActor (one vmapped call + one fused fetch per
        step for ALL groups) emits trajectories identical to k
        independent AccumVectorActors with the same per-group seeds
        (VERDICT r3 item 3)."""
        from scalable_agent_tpu.runtime.accum_actor import (
            GroupedAccumActor)

        agent, params = agent_and_params
        k = 2
        groups_fused = [make_envs() for _ in range(k)]
        groups_solo = [make_envs() for _ in range(k)]
        try:
            programs = AccumPrograms(agent, T, B, FRAME.shape)
            fused = GroupedAccumActor(
                programs, groups_fused,
                seeds=[1000 * i for i in range(k)])
            solos = [AccumVectorActor(programs, envs, seed=1000 * i)
                     for i, envs in enumerate(groups_solo)]
            for _ in range(2):
                fused_outs = fused.run_unroll(params)
                solo_outs = [s.run_unroll(params) for s in solos]
                assert len(fused_outs) == k
                for f, s in zip(fused_outs, solo_outs):
                    f, s = tree_as_numpy(f), tree_as_numpy(s)
                    np.testing.assert_array_equal(
                        f.env_outputs.observation.frame,
                        s.env_outputs.observation.frame)
                    np.testing.assert_array_equal(
                        f.agent_outputs.action, s.agent_outputs.action)
                    np.testing.assert_allclose(
                        f.agent_outputs.policy_logits,
                        s.agent_outputs.policy_logits,
                        rtol=1e-5, atol=1e-6)
                    np.testing.assert_allclose(
                        f.agent_state.c, s.agent_state.c,
                        rtol=1e-5, atol=1e-6)
        finally:
            for g in groups_fused + groups_solo:
                g.close()

    def test_fused_shards_split_fleet_and_match(self, agent_and_params):
        """fused_shards=2 over 3 groups -> two lockstep drivers (2+1
        groups) whose trajectories still match the threaded path's
        per-group seeds."""
        agent, params = agent_and_params
        groups = [make_envs(B, workers=1) for _ in range(3)]
        solo_groups = [make_envs(B, workers=1) for _ in range(3)]
        pool = ActorPool(agent, groups, unroll_length=T, seed=11,
                         inference_mode="accum_fused", fused_shards=2)
        try:
            assert len(pool._actors) == 2
            assert [len(a.envs_list) for a in pool._actors] == [2, 1]
            programs = pool._actors[0]._p
            solos = [AccumVectorActor(programs, envs, seed=11 + 1000 * i)
                     for i, envs in enumerate(solo_groups)]
            fused_outs = (pool._actors[0].run_unroll(params)
                          + pool._actors[1].run_unroll(params))
            for f, s in zip(fused_outs,
                            [a.run_unroll(params) for a in solos]):
                np.testing.assert_array_equal(
                    np.asarray(f.agent_outputs.action),
                    np.asarray(s.agent_outputs.action))
        finally:
            for g in groups + solo_groups:
                g.close()
            for actor in pool._actors:
                actor.envs_list = []  # groups already closed above

    def test_pool_accum_fused_feeds_learner(self, agent_and_params):
        """End-to-end: ActorPool(inference_mode='accum_fused') -> Learner
        with per-group trajectories arriving through the queue."""
        agent, params = agent_and_params
        mesh = make_mesh(MeshSpec(data=B, model=1),
                         devices=jax.devices()[:B])
        hp = LearnerHyperparams(total_environment_frames=1e6)
        learner = Learner(agent, hp, mesh, frames_per_update=T * B)
        groups = [make_envs(B, workers=2) for _ in range(2)]
        pool = ActorPool(agent, groups, unroll_length=T, seed=11,
                         inference_mode="accum_fused")
        pool.set_params(params)
        assert pool.num_envs == 2 * B
        pool.start()
        try:
            state = None
            for _ in range(4):
                out = pool.get_trajectory(timeout=60)
                traj = Trajectory(
                    agent_state=out.agent_state,
                    env_outputs=out.env_outputs,
                    agent_outputs=out.agent_outputs)
                assert traj.agent_outputs.action.shape == (T + 1, B)
                if state is None:
                    state = learner.init(jax.random.key(4), traj)
                state, metrics = learner.update(
                    state, learner.put_trajectory(traj))
                pool.set_params(state.params)
            assert np.isfinite(float(metrics["total_loss"]))
            assert len(pool.episode_stats()) > 0
        finally:
            pool.stop()


class TestActorPoolAccumMode:
    def test_pool_accum_feeds_learner(self, agent_and_params):
        agent, params = agent_and_params
        mesh = make_mesh(MeshSpec(data=B, model=1),
                         devices=jax.devices()[:B])
        hp = LearnerHyperparams(total_environment_frames=1e6)
        learner = Learner(agent, hp, mesh, frames_per_update=T * B)
        groups = [make_envs(B, workers=2) for _ in range(2)]
        pool = ActorPool(agent, groups, unroll_length=T, seed=11,
                         inference_mode="accum")
        pool.set_params(params)
        pool.start()
        try:
            state = None
            for _ in range(3):
                out = pool.get_trajectory(timeout=60)
                traj = Trajectory(
                    agent_state=out.agent_state,
                    env_outputs=out.env_outputs,
                    agent_outputs=out.agent_outputs)
                assert traj.agent_outputs.action.shape == (T + 1, B)
                if state is None:
                    state = learner.init(jax.random.key(4), traj)
                state, metrics = learner.update(
                    state, learner.put_trajectory(traj))
                pool.set_params(state.params)
            assert np.isfinite(float(metrics["total_loss"]))
            assert float(metrics["env_frames"]) == 3 * T * B
        finally:
            pool.stop()

    def test_accum_rejects_ragged_groups(self, agent_and_params):
        agent, _ = agent_and_params
        groups = [make_envs(2, workers=1), make_envs(3, workers=1)]
        try:
            with pytest.raises(ValueError, match="uniform group sizes"):
                ActorPool(agent, groups, unroll_length=T,
                          inference_mode="accum")
        finally:
            for g in groups:
                g.close()
