"""On-device trajectory accumulation (runtime/accum_actor.py).

The accum path must be a pure data-flow optimization: given identical env
seeds, params, and rng seeds it must emit byte-identical trajectories to
the structural ``VectorActor`` path — same [T+1, B] layout, same overlap
entry, same rng stream (the learner cannot tell which actor produced a
batch).  Plus an end-to-end ActorPool(inference_mode='accum') → Learner
consumption test mirroring the structural/service ones.
"""

import functools

import jax
import numpy as np
import pytest

from scalable_agent_tpu.envs import MultiEnv, make_impala_stream
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.models import ImpalaAgent
from scalable_agent_tpu.models import agent as agent_mod
from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.runtime import (
    ActorPool,
    Learner,
    LearnerHyperparams,
    Trajectory,
    VectorActor,
)
from scalable_agent_tpu.runtime.accum_actor import (
    AccumPrograms,
    AccumVectorActor,
)

NUM_ACTIONS = 5
FRAME = TensorSpec((16, 16, 3), np.uint8, "frame")
T = 6
B = 4


def make_envs(n=B, workers=2):
    fns = [functools.partial(make_impala_stream, "fake_small", seed=i,
                             num_actions=NUM_ACTIONS)
           for i in range(n)]
    return MultiEnv(fns, FRAME, num_workers=workers)


@pytest.fixture(scope="module")
def agent_and_params():
    agent = ImpalaAgent(num_actions=NUM_ACTIONS)
    envs = make_envs(1, workers=1)
    try:
        params = agent.init(
            jax.random.key(0),
            np.zeros((1, 1), np.int32),
            jax.tree_util.tree_map(
                lambda x: None if x is None else np.asarray(x)[None][:, :1],
                envs.initial(), is_leaf=lambda x: x is None),
            agent_mod.initial_state(1))
    finally:
        envs.close()
    return agent, params


def tree_as_numpy(tree):
    return jax.tree_util.tree_map(
        lambda x: None if x is None else np.asarray(x), tree,
        is_leaf=lambda x: x is None)


class TestEquivalence:
    def test_trajectories_match_structural_path(self, agent_and_params):
        agent, params = agent_and_params
        envs_a = make_envs()
        envs_b = make_envs()
        try:
            structural = VectorActor(agent, envs_a, T, seed=7)
            programs = AccumPrograms(agent, T, B, FRAME.shape)
            accum = AccumVectorActor(programs, envs_b, seed=7)
            for unroll_index in range(3):
                out_s = structural.run_unroll(params)
                out_a = accum.run_unroll(params)
                s = tree_as_numpy(out_s)
                a = tree_as_numpy(out_a)
                np.testing.assert_array_equal(
                    s.env_outputs.observation.frame,
                    a.env_outputs.observation.frame,
                    err_msg=f"frames diverge at unroll {unroll_index}")
                np.testing.assert_array_equal(
                    s.agent_outputs.action, a.agent_outputs.action)
                np.testing.assert_array_equal(
                    s.env_outputs.done, a.env_outputs.done)
                np.testing.assert_allclose(
                    s.env_outputs.reward, a.env_outputs.reward, rtol=1e-6)
                np.testing.assert_allclose(
                    s.env_outputs.info.episode_return,
                    a.env_outputs.info.episode_return, rtol=1e-6)
                np.testing.assert_array_equal(
                    s.env_outputs.info.episode_step,
                    a.env_outputs.info.episode_step)
                np.testing.assert_allclose(
                    s.agent_outputs.policy_logits,
                    a.agent_outputs.policy_logits, rtol=1e-5, atol=1e-6)
                np.testing.assert_allclose(
                    s.agent_outputs.baseline, a.agent_outputs.baseline,
                    rtol=1e-5, atol=1e-6)
                np.testing.assert_allclose(
                    s.agent_state.c, a.agent_state.c, rtol=1e-5, atol=1e-6)
                np.testing.assert_allclose(
                    s.agent_state.h, a.agent_state.h, rtol=1e-5, atol=1e-6)
        finally:
            envs_a.close()
            envs_b.close()

    def test_overlap_entry_carries_across_unrolls(self, agent_and_params):
        """Entry 0 of unroll k+1 == entry T of unroll k (reference
        trajectory layout, experiment.py:311-321)."""
        agent, params = agent_and_params
        envs = make_envs()
        try:
            programs = AccumPrograms(agent, T, B, FRAME.shape)
            actor = AccumVectorActor(programs, envs, seed=3)
            first = tree_as_numpy(actor.run_unroll(params))
            second = tree_as_numpy(actor.run_unroll(params))
            np.testing.assert_array_equal(
                first.env_outputs.observation.frame[T],
                second.env_outputs.observation.frame[0])
            np.testing.assert_array_equal(
                first.agent_outputs.action[T],
                second.agent_outputs.action[0])
            np.testing.assert_allclose(
                first.agent_outputs.policy_logits[T],
                second.agent_outputs.policy_logits[0])
        finally:
            envs.close()


class TestActorPoolAccumMode:
    def test_pool_accum_feeds_learner(self, agent_and_params):
        agent, params = agent_and_params
        mesh = make_mesh(MeshSpec(data=B, model=1),
                         devices=jax.devices()[:B])
        hp = LearnerHyperparams(total_environment_frames=1e6)
        learner = Learner(agent, hp, mesh, frames_per_update=T * B)
        groups = [make_envs(B, workers=2) for _ in range(2)]
        pool = ActorPool(agent, groups, unroll_length=T, seed=11,
                         inference_mode="accum")
        pool.set_params(params)
        pool.start()
        try:
            state = None
            for _ in range(3):
                out = pool.get_trajectory(timeout=60)
                traj = Trajectory(
                    agent_state=out.agent_state,
                    env_outputs=out.env_outputs,
                    agent_outputs=out.agent_outputs)
                assert traj.agent_outputs.action.shape == (T + 1, B)
                if state is None:
                    state = learner.init(jax.random.key(4), traj)
                state, metrics = learner.update(
                    state, learner.put_trajectory(traj))
                pool.set_params(state.params)
            assert np.isfinite(float(metrics["total_loss"]))
            assert float(metrics["env_frames"]) == 3 * T * B
        finally:
            pool.stop()

    def test_accum_rejects_ragged_groups(self, agent_and_params):
        agent, _ = agent_and_params
        groups = [make_envs(2, workers=1), make_envs(3, workers=1)]
        try:
            with pytest.raises(ValueError, match="uniform group sizes"):
                ActorPool(agent, groups, unroll_length=T,
                          inference_mode="accum")
        finally:
            for g in groups:
                g.close()
