"""NativeBatcher (C++ core) tests.

Ports the reference's full batching-semantics matrix (reference:
dynamic_batching_test.py — co-batching :63-78, timeout wall-clock
:242-275, max-size partitioning :277-298, error propagation :101-200,
cancellation on close :202-240, out-of-order completion :334-375) to the
ctypes front-end, plus pytree layouts, padding, and a ThreadSanitizer
variant run the reference never had (it relied on compile-time lock
annotations only, batcher.cc:182-204).
"""

import os
import subprocess
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from scalable_agent_tpu.native.build import build_library
from scalable_agent_tpu.runtime import BatcherClosedError
from scalable_agent_tpu.runtime.native_batcher import NativeBatcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def scalar_batcher(fn, **kwargs):
    kwargs.setdefault("timeout_ms", 50.0)
    return NativeBatcher(
        fn, example_sample=np.float32(0), example_result=np.float32(0),
        **kwargs)


class TestNativeBatcherCore:
    def test_single_call_roundtrip(self):
        with scalar_batcher(lambda x, n: x * 2) as b:
            assert b.compute(np.float32(21)) == 42

    def test_multi_element_leaves(self):
        """Regression: result leaves with >1 element per row must scatter
        correctly (the round-1 wrapper crashed reshaping element counts to
        byte counts)."""
        example = {"vec": np.zeros(3, np.float32),
                   "mat": np.zeros((2, 2), np.int32)}

        def fn(batch, n):
            return {"vec": batch["vec"] + 1.0, "mat": batch["mat"] * 2}

        with NativeBatcher(fn, example, example, timeout_ms=20) as b:
            out = b.compute({"vec": np.arange(3, dtype=np.float32),
                             "mat": np.arange(4, dtype=np.int32).reshape(2, 2)})
        np.testing.assert_array_equal(out["vec"], [1.0, 2.0, 3.0])
        np.testing.assert_array_equal(out["mat"], [[0, 2], [4, 6]])

    def test_mixed_dtype_pytree(self):
        example_s = {"f": np.zeros((4,), np.float32),
                     "b": np.zeros((), np.bool_),
                     "u": np.zeros((2,), np.uint8)}
        example_r = {"sum": np.zeros((), np.float32)}

        def fn(batch, n):
            total = batch["f"].sum(-1) + batch["b"] + batch["u"].sum(-1)
            return {"sum": total.astype(np.float32)}

        with NativeBatcher(fn, example_s, example_r, timeout_ms=20) as b:
            out = b.compute({"f": np.full((4,), 0.5, np.float32),
                             "b": np.bool_(True),
                             "u": np.array([3, 4], np.uint8)})
        np.testing.assert_allclose(out["sum"], 2.0 + 1.0 + 7.0)

    def test_co_batching(self):
        sizes = []

        def fn(x, n):
            sizes.append(n)
            return x + 1

        with scalar_batcher(fn, minimum_batch_size=4,
                            timeout_ms=5000) as b:
            with ThreadPoolExecutor(8) as pool:
                results = list(pool.map(
                    lambda i: b.compute(np.float32(i)), range(8)))
        assert sorted(float(r) for r in results) == list(
            map(float, range(1, 9)))
        assert all(s >= 4 or sum(sizes) == 8 for s in sizes)

    def test_timeout_flushes_partial_batch(self):
        """(reference: dynamic_batching_test.py:242-275 wall-clock)"""
        with scalar_batcher(lambda x, n: x, minimum_batch_size=32,
                            timeout_ms=50) as b:
            t0 = time.monotonic()
            result = b.compute(np.float32(7))
            elapsed = time.monotonic() - t0
        assert result == 7
        assert 0.03 <= elapsed < 2.0

    def test_no_timeout_waits_for_min_batch(self):
        """timeout_ms=None means wait for min_batch however long."""
        with scalar_batcher(lambda x, n: x, minimum_batch_size=2,
                            timeout_ms=None) as b:
            got = []

            def call(v):
                got.append(float(b.compute(np.float32(v))))

            t = threading.Thread(target=call, args=(1.0,))
            t.start()
            time.sleep(0.2)
            assert not got  # still waiting for a partner
            assert float(b.compute(np.float32(2.0))) == 2.0
            t.join(timeout=5)
        assert got == [1.0]

    def test_max_batch_size_partitions(self):
        sizes = []

        def fn(x, n):
            sizes.append(n)
            return x

        with scalar_batcher(fn, minimum_batch_size=1, maximum_batch_size=2,
                            timeout_ms=100) as b:
            with ThreadPoolExecutor(6) as pool:
                list(pool.map(lambda i: b.compute(np.float32(i)), range(6)))
        assert max(sizes) <= 2 and sum(sizes) == 6

    def test_out_of_order_completion(self):
        """Two in-flight batches complete in reverse order; results still
        reach the right callers (reference: :334-375)."""
        release_first = threading.Event()
        started = threading.Event()

        def fn(x, n):
            if float(np.min(x)) == 0.0:  # first batch: stall
                started.set()
                assert release_first.wait(timeout=10)
            return x * 10

        with scalar_batcher(fn, minimum_batch_size=1, maximum_batch_size=1,
                            timeout_ms=5, num_consumers=2) as b:
            with ThreadPoolExecutor(2) as pool:
                f0 = pool.submit(b.compute, np.float32(0))
                assert started.wait(timeout=10)
                f1 = pool.submit(b.compute, np.float32(1))
                # Second batch completes while the first is stalled.
                assert float(f1.result(timeout=10)) == 10.0
                assert not f0.done()
                release_first.set()
                assert float(f0.result(timeout=10)) == 0.0

    def test_compute_error_cascades_to_callers(self):
        def fn(x, n):
            raise ValueError("deliberate compute failure")

        with scalar_batcher(fn, timeout_ms=10) as b:
            with pytest.raises(ValueError, match="deliberate"):
                b.compute(np.float32(1))

    def test_close_cancels_pending_callers(self):
        """(reference: :202-240 cancellation on session close)"""
        b = scalar_batcher(lambda x, n: x, minimum_batch_size=16,
                           timeout_ms=None)
        errors = []

        def call():
            try:
                b.compute(np.float32(1))
            except BatcherClosedError as exc:
                errors.append(exc)

        threads = [threading.Thread(target=call) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        b.close()
        for t in threads:
            t.join(timeout=5)
        assert len(errors) == 3

    def test_compute_after_close_raises(self):
        b = scalar_batcher(lambda x, n: x)
        b.close()
        with pytest.raises(BatcherClosedError):
            b.compute(np.float32(1))

    def test_pad_to_sizes(self):
        seen = []

        def fn(x, n):
            seen.append((x.shape[0], n))
            return x[:n] + 1  # padded rows are dropped by pack_rows(n)

        with scalar_batcher(fn, minimum_batch_size=1, maximum_batch_size=8,
                            pad_to_sizes=[4, 8], timeout_ms=20) as b:
            assert float(b.compute(np.float32(1))) == 2.0
        padded_shape, n = seen[0]
        assert n == 1 and padded_shape == 4

    def test_padding_bounds_shapes_under_varying_arrival_counts(self):
        """Bursts of different sizes must all land on pad_to_sizes
        shapes — the property that bounds jit recompiles of the consumer
        computation to len(pad_to_sizes) regardless of arrival pattern
        (VERDICT r2 weak item 8)."""
        import threading

        seen = []

        def fn(x, n):
            seen.append((x.shape[0], n))
            return x[:n] * 10

        with scalar_batcher(fn, minimum_batch_size=1,
                            maximum_batch_size=8, pad_to_sizes=[2, 4, 8],
                            timeout_ms=30) as batcher:
            for burst in (1, 3, 5):
                results = [None] * burst
                def call(i):
                    results[i] = batcher.compute(np.float32(i))
                threads = [threading.Thread(target=call, args=(i,))
                           for i in range(burst)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                for i in range(burst):
                    assert float(results[i]) == i * 10.0
        padded_shapes = {shape for shape, _ in seen}
        assert padded_shapes <= {2, 4, 8}, padded_shapes
        # every batch's real count fits inside its padded shape
        assert all(n <= shape for shape, n in seen), seen

    def test_min_greater_than_max_rejected(self):
        with pytest.raises(ValueError):
            scalar_batcher(lambda x, n: x, minimum_batch_size=8,
                           maximum_batch_size=4)

    def test_shape_mismatch_raises(self):
        with NativeBatcher(lambda x, n: x, np.zeros(3, np.float32),
                           np.zeros(3, np.float32), timeout_ms=10) as b:
            with pytest.raises(ValueError, match="shape"):
                b.compute(np.zeros(4, np.float32))


@pytest.mark.slow
class TestSanitizers:
    """Actually RUN the sanitizer builds (SURVEY §5.2: the reference has
    compile-time annotations only).  The instrumented .so needs the TSan
    runtime preloaded, so the workload runs in a subprocess."""

    WORKLOAD = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from concurrent.futures import ThreadPoolExecutor
from scalable_agent_tpu.runtime.native_batcher import NativeBatcher

with NativeBatcher(lambda x, n: x + 1, np.float32(0), np.float32(0),
                   minimum_batch_size=2, maximum_batch_size=8,
                   timeout_ms=5.0, num_consumers=2,
                   variant={variant!r}) as b:
    with ThreadPoolExecutor(16) as pool:
        out = list(pool.map(lambda i: float(b.compute(np.float32(i))),
                            range(200)))
assert sorted(out) == [float(i + 1) for i in range(200)], "wrong results"
print("WORKLOAD_OK")
"""

    def _runtime_lib(self, name):
        path = subprocess.run(
            ["g++", f"-print-file-name={name}"],
            capture_output=True, text=True).stdout.strip()
        return path if path and os.path.isabs(path) else None

    def test_tsan_concurrent_workload(self):
        tsan = self._runtime_lib("libtsan.so")
        if tsan is None:
            tsan = self._runtime_lib("libtsan.so.2")
        if tsan is None:
            pytest.skip("libtsan runtime not found")
        build_library("tsan")
        env = dict(os.environ, LD_PRELOAD=tsan,
                   TSAN_OPTIONS="exitcode=66 report_thread_leaks=0",
                   JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c",
             self.WORKLOAD.format(repo=REPO, variant="tsan")],
            capture_output=True, text=True, env=env, timeout=300)
        batcher_races = [
            line for line in proc.stderr.splitlines()
            if "WARNING: ThreadSanitizer" in line]
        # CPython itself is not TSan-clean; fail only on reports that
        # implicate the batcher library or wrapper.
        implicated = "batcher" in proc.stderr and batcher_races
        assert "WORKLOAD_OK" in proc.stdout, (
            f"workload failed rc={proc.returncode}:\n{proc.stderr[-2000:]}")
        assert not implicated, proc.stderr[-4000:]
