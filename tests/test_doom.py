"""Doom env layer tests — hermetic via tests/fakes/vizdoom.py.

Covers the reference behaviors the layer reproduces: composite action
conversion (one-hot with noop-0, Discretized grids, Box delta scaling),
the VizdoomEnv-equivalent core (lazy init, black terminal screen, game
variables, stale-counter workaround), the DoomSpec wrapper pipeline
(battle measurements + shaping, benchmark convention), multiplayer
host/join + bots + lockstep, the multi-agent aggregator feeding the
ActorPool, and a real (tiny) driver train run on doom_benchmark.
"""

import functools
import os
import sys

import numpy as np
import pytest

FAKES_DIR = os.path.join(os.path.dirname(__file__), "fakes")


@pytest.fixture(scope="module", autouse=True)
def fake_vizdoom(tmp_path_factory):
    """Shadow ``vizdoom`` with the deterministic fake and generate
    scenario .cfg files (sys.path is inherited by spawned env worker
    subprocesses; DOOM_SCENARIOS_DIR rides os.environ)."""
    scenarios = tmp_path_factory.mktemp("scenarios")
    single = ("HEALTH ARMOR SELECTED_WEAPON SELECTED_WEAPON_AMMO "
              "FRAGCOUNT DEATHCOUNT HITCOUNT DAMAGECOUNT DEAD "
              "POSITION_X POSITION_Y")
    multi = single + " PLAYER_NUM PLAYER_COUNT PLAYER1_FRAGCOUNT PLAYER2_FRAGCOUNT"
    cfgs = {
        "basic.cfg": single,
        "battle.cfg": single,
        "battle_continuous_turning.cfg": single,
        "health_gathering.cfg": "HEALTH",
        "two_colors_easy.cfg": "HEALTH",
        "ssl2.cfg": multi,
        "dwango5_dm_continuous_weap.cfg": multi,
    }
    for name, variables in cfgs.items():
        (scenarios / name).write_text(
            "# fake scenario for hermetic tests\n"
            f"doom_scenario_path = {name.replace('.cfg', '.wad')}\n"
            f"available_game_variables = {{ {variables} }}\n")
    sys.path.insert(0, FAKES_DIR)
    os.environ["DOOM_SCENARIOS_DIR"] = str(scenarios)
    sys.modules.pop("vizdoom", None)
    yield
    sys.path.remove(FAKES_DIR)
    sys.modules.pop("vizdoom", None)
    os.environ.pop("DOOM_SCENARIOS_DIR", None)


class TestActionSpaces:
    def test_variant_shapes(self):
        from scalable_agent_tpu.envs import doom as d
        from scalable_agent_tpu.envs.spaces import (
            calc_num_actions, calc_num_logits)

        assert calc_num_actions(d.doom_action_space_basic()) == 2
        assert calc_num_logits(d.doom_action_space_basic()) == 6
        assert calc_num_actions(
            d.doom_action_space_discretized_no_weap()) == 5
        assert calc_num_logits(
            d.doom_action_space_discretized_no_weap()) == 3 + 3 + 2 + 2 + 11
        full = d.doom_action_space_full_discretized(with_use=True)
        assert calc_num_actions(full) == 7
        assert calc_num_logits(full) == 3 + 3 + 8 + 2 + 2 + 2 + 21

    def test_convert_one_hot_noop(self):
        from scalable_agent_tpu.envs.doom.core import convert_actions
        from scalable_agent_tpu.envs.doom import doom_action_space_basic

        space = doom_action_space_basic()
        assert convert_actions(space, (0, 0)) == [0, 0, 0, 0]
        assert convert_actions(space, (1, 2)) == [1, 0, 0, 1]

    def test_convert_discretized_grid(self):
        from scalable_agent_tpu.envs.doom.core import convert_actions
        from scalable_agent_tpu.envs.doom import (
            doom_action_space_discretized_no_weap)

        space = doom_action_space_discretized_no_weap()
        flat = convert_actions(space, (0, 0, 0, 0, 0))
        assert flat[-1] == -10.0  # Discretized(11, -10, 10) index 0
        flat = convert_actions(space, (0, 0, 0, 0, 10))
        assert flat[-1] == 10.0
        flat = convert_actions(space, (0, 0, 0, 0, 5))
        assert flat[-1] == 0.0

    def test_convert_box_scaling(self):
        from scalable_agent_tpu.envs.doom.core import convert_actions
        from scalable_agent_tpu.envs.doom import doom_action_space

        space = doom_action_space()
        flat = convert_actions(
            space, (0, 0, 0, 0, 0, np.asarray([0.5], np.float32)))
        assert flat[-1] == pytest.approx(0.5 * 7.5)  # delta scaling

    def test_convert_plain_discrete(self):
        from scalable_agent_tpu.envs.doom.core import convert_actions
        from scalable_agent_tpu.envs.spaces import Discrete

        assert convert_actions(Discrete(9), 3) == [0, 0, 1, 0, 0, 0, 0, 0]


class TestDoomEnvCore:
    def test_benchmark_env_lifecycle(self):
        from scalable_agent_tpu.envs import create_env

        env = create_env("doom_benchmark", num_action_repeats=4)
        try:
            assert env.observation_spec.frame.shape == (72, 128, 3)
            obs = env.reset()
            assert obs.frame.shape == (72, 128, 3)
            total_steps = 0
            done = False
            while not done:
                obs, reward, done, info = env.step(3)
                total_steps += 1
                assert isinstance(float(reward), float)
            # 64 fake tics / 4-skip
            assert total_steps == 16
            assert "HEALTH" in info
            # terminal observation is the black screen
            assert not obs.frame.any()
        finally:
            env.close()

    def test_game_variable_info_and_bug_workaround(self):
        from scalable_agent_tpu.envs import create_env

        env = create_env("doom_benchmark", num_action_repeats=4)
        try:
            env.reset()
            _, _, done, info = env.step(0)
            assert info["HEALTH"] == pytest.approx(100.0 - 4)
            while not done:
                _, _, done, info1 = env.step(0)
            # Second episode: DEATHCOUNT/HITCOUNT/DAMAGECOUNT subtract
            # the previous episode's final values (the VizDoom
            # stale-variable workaround, reference doom_gym.py:310-319).
            env.reset()
            _, _, _, info2 = env.step(0)
            raw_hit = 4 // 4  # fake: HITCOUNT = tic // 4 at tic 4
            assert info2["HITCOUNT"] == pytest.approx(
                raw_hit - info1["HITCOUNT"])
        finally:
            env.close()

    def test_missing_scenario_errors_clearly(self):
        from scalable_agent_tpu.envs.doom.core import resolve_scenario_path

        with pytest.raises(FileNotFoundError, match="nope.cfg"):
            resolve_scenario_path("nope.cfg")


class TestDoomPipeline:
    def test_battle_composite_pipeline(self):
        from scalable_agent_tpu.envs import create_env
        from scalable_agent_tpu.envs.spaces import TupleSpace

        env = create_env("doom_battle", num_action_repeats=4)
        try:
            assert isinstance(env.action_space, TupleSpace)
            obs = env.reset()
            # measurements vector from DoomAdditionalInput (7 + 2*8)
            assert obs.measurements.shape == (23,)
            spec = env.observation_spec
            assert spec.measurements.shape == (23,)
            obs, reward, done, info = env.step((1, 0, 1, 0, 5))
            assert obs.measurements[2] == pytest.approx(
                info["HEALTH"] / 30.0)
            assert "true_reward" not in info  # only set on done
        finally:
            env.close()

    def test_battle_reward_shaping_applies(self):
        from scalable_agent_tpu.envs import create_env

        env = create_env("doom_battle", num_action_repeats=4)
        try:
            env.reset()
            env.step((0, 0, 0, 0, 5))  # first step primes prev_vars
            _, reward2, _, info = env.step((0, 0, 0, 0, 5))
            # fake raw per-step reward at tics 5..8
            raw = sum((t % 5) * 0.1 for t in (5, 6, 7, 8))
            # HITCOUNT +1/step * 0.01, DAMAGECOUNT +3 * 0.003,
            # HEALTH -4 * 0.003 (down-rate), ARMOR cycles mod 7
            assert float(reward2) != pytest.approx(raw)
        finally:
            env.close()

    def test_impala_stream_native_repeats(self):
        from scalable_agent_tpu.envs import make_impala_stream

        stream = make_impala_stream("doom_benchmark", seed=3,
                                    num_action_repeats=4)
        try:
            stream.initial()
            # Exactly 16 agent steps per 64-tic fake episode: the
            # simulator's native make_action skip must NOT be doubled by
            # an extra SkipFramesWrapper (4x4=16 tics/step would end the
            # episode after 4 agent steps).
            steps_to_done = 0
            done = False
            while not done:
                out = stream.step(1)
                steps_to_done += 1
                done = bool(out.done)
                assert steps_to_done <= 16, "episode ended late"
            assert steps_to_done == 16, steps_to_done
        finally:
            stream.close()


class TestAccumMeasurements:
    def test_accum_matches_structural_on_battle(self):
        """Accum == structural on a measurements-carrying Doom level:
        the DoomAdditionalInput f32 vector rides the per-step upload
        into its own device buffer (VERDICT r3 item 6; reference:
        envs/doom/wrappers/additional_input.py:7-96)."""
        import functools

        import jax

        from scalable_agent_tpu.envs import (
            MultiEnv, create_env, make_impala_stream)
        from scalable_agent_tpu.envs.spec import TensorSpec
        from scalable_agent_tpu.models import ImpalaAgent
        from scalable_agent_tpu.models import agent as agent_mod
        from scalable_agent_tpu.runtime import VectorActor
        from scalable_agent_tpu.runtime.accum_actor import (
            AccumPrograms, AccumVectorActor)
        from scalable_agent_tpu.types import AgentOutput

        t, b = 4, 2
        probe = create_env("doom_battle", num_action_repeats=4,
                           width=64, height=36)
        try:
            spec = probe.observation_spec
            action_space = probe.action_space
        finally:
            probe.close()
        assert spec.measurements is not None
        frame = TensorSpec(tuple(spec.frame.shape), np.uint8, "frame")
        agent = ImpalaAgent(action_space=action_space)

        def make_group():
            fns = [functools.partial(
                make_impala_stream, "doom_battle", seed=100 + i,
                num_action_repeats=4, width=64, height=36)
                for i in range(b)]
            return MultiEnv(fns, frame, num_workers=1)

        envs_a = make_group()
        envs_b = make_group()
        try:
            init_out = envs_a.initial()
            assert init_out.observation.measurements is not None
            params = agent.init(
                jax.random.key(0),
                np.asarray(agent.zero_actions(b))[None],
                jax.tree_util.tree_map(
                    lambda x: None if x is None else np.asarray(x)[None],
                    init_out, is_leaf=lambda x: x is None),
                agent_mod.initial_state(b))
            structural = VectorActor(agent, envs_a, t, seed=5)
            structural._last_env_output = init_out  # reuse the probe
            structural._core_state = agent_mod.initial_state(b)
            structural._last_agent_output = AgentOutput(
                action=np.asarray(agent.zero_actions(b)),
                policy_logits=np.zeros((b, agent.num_logits), np.float32),
                baseline=np.zeros((b,), np.float32))
            programs = AccumPrograms(
                agent, t, b, frame.shape,
                measurements_shape=tuple(spec.measurements.shape))
            accum = AccumVectorActor(programs, envs_b, seed=5)
            for _ in range(2):
                s = structural.run_unroll(params)
                a = accum.run_unroll(params)
                np.testing.assert_allclose(
                    np.asarray(s.env_outputs.observation.measurements),
                    np.asarray(a.env_outputs.observation.measurements),
                    rtol=1e-6)
                np.testing.assert_array_equal(
                    np.asarray(s.agent_outputs.action),
                    np.asarray(a.agent_outputs.action))
                np.testing.assert_allclose(
                    np.asarray(s.agent_outputs.policy_logits),
                    np.asarray(a.agent_outputs.policy_logits),
                    rtol=1e-5, atol=1e-6)
        finally:
            envs_a.close()
            envs_b.close()


class TestMultiplayer:
    def test_bots_host_setup(self):
        from scalable_agent_tpu.envs import create_env

        env = create_env("doom_deathmatch_bots", num_action_repeats=4)
        try:
            env.reset()
            game = env.unwrapped.game
            assert any("-host 1" in a for a in game.args)
            assert "removebots" in game.commands
            assert sum(
                1 for c in game.commands if c.startswith("addbot")) == 7
            obs, reward, done, info = env.step((0, 0, 0, 0, 0, 10))
            assert obs.measurements is not None
        finally:
            env.close()

    def test_duel_lockstep_two_agents(self):
        from scalable_agent_tpu.envs import create_env

        env = create_env("doom_duel", num_action_repeats=4)
        try:
            assert env.num_agents == 2
            obs = env.reset()
            assert len(obs) == 2
            action = (0, 0, 0, 0, 0, 0, 10)
            obs, rewards, dones, infos = env.step([action, action])
            assert len(obs) == len(rewards) == len(dones) == 2
            assert not any(dones)
            # 4-frameskip via lockstep: 3 silent ticks + 1 update tick
            for _ in range(15):
                obs, rewards, dones, infos = env.step([action, action])
            assert all(dones)
            # post-done observations come from the auto-reset
            assert obs[0].frame.shape == (72, 128, 3)
        finally:
            env.close()

    def test_per_player_recording(self, tmp_path):
        """record_to on a multi-agent level: each player writes its own
        episode stream under player_NN (role of the reference's record
        path, envs/env_wrappers.py:433-497, extended to multi-agent)."""
        from scalable_agent_tpu.envs import create_env

        record_dir = tmp_path / "rec"
        env = create_env("doom_duel", num_action_repeats=4,
                         record_to=str(record_dir))
        try:
            env.reset()
            action = (0, 0, 0, 0, 0, 0, 10)
            for _ in range(16):  # past one episode boundary
                env.step([action, action])
        finally:
            env.close()  # flushes the in-flight episode
        import json as json_lib

        for player in ("player_00", "player_01"):
            episodes = sorted((record_dir / player).glob("episode_*"))
            assert episodes, f"no recordings for {player}"
            # Consecutive numbering from 0 — the worker-INIT double
            # reset must not leave a degenerate leading episode.
            assert episodes[0].name == "episode_00000"
            frames = np.load(episodes[0] / "frames.npy")
            meta = json_lib.load(open(episodes[0] / "episode.json"))
            # Real gameplay, not a reset artifact: steps were recorded
            # and frames = initial + one per action.
            assert len(meta["actions"]) >= 1
            assert len(meta["actions"]) == len(meta["rewards"])
            assert frames.shape[0] == len(meta["actions"]) + 1
            assert frames.ndim == 4 and frames.shape[-1] == 3

    def test_host_and_join_args(self):
        from scalable_agent_tpu.envs.doom.multiplayer import (
            DoomMultiplayerEnv)
        from scalable_agent_tpu.envs.doom import doom_action_space_basic

        host = DoomMultiplayerEnv(
            doom_action_space_basic(), "ssl2.cfg", player_id=0,
            num_agents=2, max_num_players=2, num_bots=0, port=40555)
        join = DoomMultiplayerEnv(
            doom_action_space_basic(), "ssl2.cfg", player_id=1,
            num_agents=2, max_num_players=2, num_bots=0, port=40555)
        try:
            host.reset()
            join.reset()
            assert any("-host 2" in a for a in host.game.args)
            assert any("-join 127.0.0.1:40555" in a
                       for a in join.game.args)
        finally:
            host.close()
            join.close()


class TestAggregator:
    def test_aggregator_feeds_actor_pool(self):
        import jax

        from scalable_agent_tpu.envs import create_env
        from scalable_agent_tpu.envs.doom.multiplayer import (
            MultiAgentVectorEnv)
        from scalable_agent_tpu.models import ImpalaAgent
        from scalable_agent_tpu.models import agent as agent_mod
        from scalable_agent_tpu.parallel import MeshSpec, make_mesh
        from scalable_agent_tpu.runtime import (
            ActorPool, Learner, LearnerHyperparams, Trajectory)

        T = 4
        vec = MultiAgentVectorEnv([
            functools.partial(create_env, "doom_duel",
                              num_action_repeats=4)
            for _ in range(2)
        ])
        assert vec.num_envs == 4
        spec = create_env("doom_duel", num_action_repeats=4)
        action_space = spec.action_space  # cheap: no games started
        spec.close()
        agent = ImpalaAgent(action_space=action_space)
        pool = ActorPool(agent, [vec], unroll_length=T, seed=5)
        out0 = vec.initial()
        params = agent.init(
            jax.random.key(0),
            np.zeros((1, 4, 7), np.int32),
            jax.tree_util.tree_map(
                lambda x: None if x is None else np.asarray(x)[None],
                out0, is_leaf=lambda x: x is None),
            agent_mod.initial_state(4))
        pool.set_params(params)
        pool.start()
        try:
            out = pool.get_trajectory(timeout=120)
            assert out.agent_outputs.action.shape == (T + 1, 4, 7)
            mesh = make_mesh(MeshSpec(data=4, model=1),
                             devices=jax.devices()[:4])
            learner = Learner(agent, LearnerHyperparams(), mesh,
                              frames_per_update=T * 4 * 4)
            traj = Trajectory(out.agent_state, out.env_outputs,
                              out.agent_outputs)
            state = learner.init(jax.random.key(1), traj)
            state, metrics = learner.update(
                state, learner.put_trajectory(traj))
            assert np.isfinite(float(np.asarray(metrics["total_loss"])))
        finally:
            pool.stop()


class TestDriverDoom:
    def test_driver_trains_on_doom_benchmark(self, tmp_path):
        """VERDICT r2 done-criterion: the driver constructs and trains
        --level_name=doom_benchmark under the fake simulator."""
        from scalable_agent_tpu.config import Config
        from scalable_agent_tpu.driver import train

        config = Config(
            mode="train",
            logdir=str(tmp_path / "logs"),
            level_name="doom_benchmark",
            num_actors=4,
            batch_size=2,
            unroll_length=3,
            num_action_repeats=4,
            num_env_workers_per_group=2,
            total_environment_frames=3 * 2 * 3 * 4,  # 3 updates
            compute_dtype="float32",
            checkpoint_interval_s=1e9,
        )
        metrics = train(config)
        assert np.isfinite(metrics["total_loss"])
        assert metrics["env_frames"] == config.total_environment_frames


class TestTools:
    def test_sample_cli_converts_numeric_args(self):
        """main() must int()-convert numeric CLI args before they reach
        range()/make_action (regression: '500' crashed sample_env)."""
        from scalable_agent_tpu.envs.doom import tools

        tools.main(["sample", "doom_basic", "8", "2", "3"])

    def test_concat_grid(self):
        from scalable_agent_tpu.envs.doom import tools

        frames = [np.full((4, 6, 3), i, np.uint8) for i in range(3)]
        grid = tools.concat_grid(frames)
        assert grid.shape == (8, 12, 3)
        assert (grid[:4, :6] == 0).all() and (grid[:4, 6:] == 1).all()


class TestHistogramAndAutomap:
    def test_position_histogram_tracks_and_rolls_over(self):
        """coord_limits enables the coverage histogram; reset archives
        it (reference: doom_gym.py:102-117, 424-438)."""
        from scalable_agent_tpu.envs.doom.core import DoomEnv
        from scalable_agent_tpu.envs.doom import doom_action_space_basic

        env = DoomEnv(doom_action_space_basic(), "battle.cfg",
                      coord_limits=(0.0, 0.0, 100.0, 50.0),
                      max_histogram_length=20)
        try:
            assert env.current_histogram.shape == (20, 10)  # aspect 2:1
            env.reset()
            for _ in range(5):
                env.step((0, 0))
            assert env.current_histogram.sum() == 5
            env.reset()
            assert env.current_histogram.sum() == 0
            assert env.previous_histogram.sum() == 5
        finally:
            env.close()

    def test_automap_buffer(self):
        from scalable_agent_tpu.envs.doom.core import DoomEnv
        from scalable_agent_tpu.envs.doom import doom_action_space_basic

        env = DoomEnv(doom_action_space_basic(), "battle.cfg",
                      show_automap=True)
        try:
            env.reset()
            env.step((0, 0))
            automap = env.get_automap_buffer()
            assert automap is not None
            h, w, _ = env.observation_spec.frame.shape
            assert automap.shape[2] == 3
            assert env.game.automap_mode == "OBJECTS"
        finally:
            env.close()

    def test_no_histogram_without_coord_limits(self):
        from scalable_agent_tpu.envs.doom.core import DoomEnv
        from scalable_agent_tpu.envs.doom import doom_action_space_basic

        env = DoomEnv(doom_action_space_basic(), "battle.cfg")
        try:
            assert env.current_histogram is None
            env.reset()
            env.step((0, 0))  # no crash without the histogram
        finally:
            env.close()


class TestExplorationWrapper:
    def test_landmark_bonus_then_silence(self):
        """A new pose earns the bonus once; staying near known
        landmarks earns nothing (reference: exploration.py:10-58)."""
        from scalable_agent_tpu.envs.doom.core import DoomEnv
        from scalable_agent_tpu.envs.doom import doom_action_space_basic
        from scalable_agent_tpu.envs.doom.wrappers import (
            DoomExplorationWrapper)

        env = DoomExplorationWrapper(
            DoomEnv(doom_action_space_basic(), "battle.cfg"),
            threshold=75.0, bonus=0.1)
        try:
            env.reset()
            _, _, _, info = env.step((0, 0))
            assert info["intrinsic_reward"] == pytest.approx(0.1)
            # fake positions advance by (13, 29) per tic — within the
            # 75.0 threshold of the first landmark, so no new bonus
            _, _, _, info = env.step((0, 0))
            assert info["intrinsic_reward"] == pytest.approx(0.0)
            # reset clears the landmark memory
            env.reset()
            _, _, _, info = env.step((0, 0))
            assert info["intrinsic_reward"] == pytest.approx(0.1)
        finally:
            env.close()


class TestInitLock:
    def test_concurrent_init_critical_sections_do_not_overlap(self):
        """Concurrent first-inits serialize on the file lock: the
        _make_game critical sections must be disjoint in time, not just
        both succeed (flock excludes between distinct fds, so two
        threads observe the same mutual exclusion processes would).
        (reference: environments_doom.py:46-57 FileLock retry loop)"""
        import threading
        import time
        from unittest import mock

        from scalable_agent_tpu.envs.doom.core import DoomEnv
        from scalable_agent_tpu.envs.doom import doom_action_space_basic

        spans = []
        orig = DoomEnv._make_game

        def slow_make(self):
            start = time.monotonic()
            time.sleep(0.3)
            game = orig(self)
            spans.append((start, time.monotonic()))
            return game

        envs = [DoomEnv(doom_action_space_basic(), "basic.cfg")
                for _ in range(2)]
        errors = []

        def init(env):
            try:
                env.reset()
            except Exception as exc:  # noqa: BLE001 - collected for assert
                errors.append(exc)

        threads = [threading.Thread(target=init, args=(e,)) for e in envs]
        try:
            with mock.patch.object(DoomEnv, "_make_game", slow_make):
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=30)
            assert not errors, errors
            assert all(e.game is not None for e in envs)
            assert len(spans) == 2
            first, second = sorted(spans)
            assert second[0] >= first[1] - 0.01, (
                f"init critical sections overlapped: {spans}")
        finally:
            for e in envs:
                e.close()


class TestStepHumanInput:
    def test_ignores_policy_action_and_advances(self):
        from scalable_agent_tpu.envs.doom.core import DoomEnv
        from scalable_agent_tpu.envs.doom import doom_action_space_basic
        from scalable_agent_tpu.envs.doom.wrappers import StepHumanInput

        env = StepHumanInput(
            DoomEnv(doom_action_space_basic(), "basic.cfg"))
        try:
            env.reset()
            game = env.unwrapped.game
            assert game.mode == "ASYNC_SPECTATOR"
            assert game.window_visible
            tic_before = game.tic
            obs, reward, done, info = env.step("not-even-an-action")
            assert game.tic == tic_before + 1
            assert obs.frame.shape == env.unwrapped.observation_spec.frame.shape
            assert info["num_frames"] == 1
            # the base env's normal step() is restored afterward
            assert "step" not in vars(env.unwrapped)
        finally:
            env.close()

    def test_human_step_flows_through_wrapper_pipeline(self):
        """Human transitions must pass through resize/measurements/
        shaping exactly like policy steps — same obs shape and fields
        within one episode."""
        from scalable_agent_tpu.envs.doom.specs import (
            assemble_doom_env, doom_spec_by_name)
        from scalable_agent_tpu.envs.doom.wrappers import StepHumanInput

        env = StepHumanInput(
            assemble_doom_env(doom_spec_by_name("doom_battle")))
        try:
            obs0 = env.reset()
            obs, reward, done, info = env.step(None)
            assert obs.frame.shape == obs0.frame.shape  # resized alike
            assert obs.measurements is not None          # DoomAdditionalInput
            assert obs.measurements.shape == obs0.measurements.shape
        finally:
            env.close()

    def test_spectator_rearmed_after_game_recreation(self):
        from scalable_agent_tpu.envs.doom.core import DoomEnv
        from scalable_agent_tpu.envs.doom import doom_action_space_basic
        from scalable_agent_tpu.envs.doom.wrappers import StepHumanInput

        env = StepHumanInput(
            DoomEnv(doom_action_space_basic(), "basic.cfg"))
        try:
            env.reset()
            env.unwrapped.close()  # game -> None
            env.reset()
            assert env.unwrapped.game.mode == "ASYNC_SPECTATOR"
        finally:
            env.close()

    def test_human_steps_update_position_histogram(self):
        from scalable_agent_tpu.envs.doom.core import DoomEnv
        from scalable_agent_tpu.envs.doom import doom_action_space_basic
        from scalable_agent_tpu.envs.doom.wrappers import StepHumanInput

        env = StepHumanInput(
            DoomEnv(doom_action_space_basic(), "battle.cfg",
                    coord_limits=(0.0, 0.0, 100.0, 50.0)))
        try:
            env.reset()
            for _ in range(4):
                env.step(None)
            assert env.unwrapped.current_histogram.sum() == 4
        finally:
            env.close()


class TestDriverMultiAgent:
    @pytest.mark.slow
    def test_driver_trains_on_multiagent_level(self, tmp_path):
        """driver --level_name=doom_duel end-to-end: make_env_groups
        auto-routes the 2-agent level into MultiAgentVectorEnv groups
        (role of the reference's create_multi_env dispatch,
        envs/env_utils.py:6-20)."""
        from scalable_agent_tpu.config import Config
        from scalable_agent_tpu.driver import train

        config = Config(
            mode="train",
            logdir=str(tmp_path / "logs"),
            level_name="doom_duel",
            num_actors=4,
            batch_size=2,  # 1 match x 2 agents per group
            unroll_length=3,
            num_action_repeats=4,
            total_environment_frames=2 * 3 * 2 * 4,  # 2 updates
            compute_dtype="float32",
            checkpoint_interval_s=1e9,
        )
        metrics = train(config)
        assert np.isfinite(metrics["total_loss"])
        assert metrics["env_frames"] == config.total_environment_frames

    @pytest.mark.slow
    def test_multiagent_eval_after_train(self, tmp_path):
        """--mode=test on a multi-agent level: self-play eval over
        lockstep matches (beyond the reference, whose eval path is
        single-agent only)."""
        from scalable_agent_tpu.config import Config
        from scalable_agent_tpu.driver import test as run_test
        from scalable_agent_tpu.driver import train

        logdir = str(tmp_path / "logs")
        common = dict(
            logdir=logdir, level_name="doom_duel",
            num_actors=4, batch_size=2, unroll_length=3,
            num_action_repeats=4, compute_dtype="float32",
            checkpoint_interval_s=0.0,
        )
        train(Config(mode="train",
                     total_environment_frames=2 * 3 * 2 * 4, **common))
        record_dir = tmp_path / "recordings"
        returns = run_test(Config(
            mode="test", test_num_episodes=4, test_batch_size=4,
            record_to=str(record_dir), **common))
        assert list(returns) == ["doom_duel"]
        assert len(returns["doom_duel"]) == 4
        assert all(np.isfinite(r) for r in returns["doom_duel"])
        # Multi-agent eval recording: per-match, per-player episode
        # files (round-4 VERDICT item 6; reference record path is
        # single-agent only, env_wrappers.py:433-497).
        match_dirs = sorted((record_dir / "doom_duel").glob("match_*"))
        assert match_dirs, "no match recording directories"
        for match in match_dirs:
            players = sorted(match.glob("player_*"))
            assert len(players) == 2, match
            for player in players:
                episodes = sorted(player.glob("episode_*"))
                assert episodes, f"no episodes recorded in {player}"
                assert (episodes[0] / "frames.npy").exists()
                assert (episodes[0] / "episode.json").exists()

    def test_batch_size_must_divide_by_agents(self, tmp_path):
        from scalable_agent_tpu.config import Config
        from scalable_agent_tpu.driver import make_env_groups
        from scalable_agent_tpu.envs.spec import TensorSpec

        config = Config(
            logdir=str(tmp_path), level_name="doom_duel",
            num_actors=3, batch_size=3)
        with pytest.raises(ValueError, match="num_agents"):
            make_env_groups(config, TensorSpec((72, 128, 3), np.uint8),
                            num_agents=2)
