"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Two layers of forcing are needed:

1. ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` must be in the
   environment before the CPU backend is *initialized* (it is read at client
   creation, which is lazy — so setting it here, before any test touches
   jax, is early enough).

2. The interpreter's sitecustomize may register an experimental TPU-tunnel
   PJRT plugin and point ``jax_platforms`` at it via ``jax.config`` — which
   overrides the ``JAX_PLATFORMS`` env var.  ``jax.config.update`` after
   import is the reliable override; without it, test processes block on a
   remote TPU claim.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Keep test compiles fast and deterministic.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# -- smoke tier ------------------------------------------------------------
# `pytest -m smoke` is the time-boxed CI selection (< 2 min on one core):
# the pure-math and protocol modules below, minus anything marked slow.
# Heavier end-to-end coverage stays in the default/-m slow tiers.

import pytest  # noqa: E402

_SMOKE_MODULES = {
    "test_vtrace",
    "test_losses",
    "test_distributions",
    "test_utils_algo",
    "test_utils_misc",
    "test_batcher",
    "test_sequence_parallel",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        module = getattr(item, "module", None)
        if (module is not None
                and module.__name__ in _SMOKE_MODULES
                and "slow" not in item.keywords):
            item.add_marker(pytest.mark.smoke)
