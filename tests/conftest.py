"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Two layers of forcing are needed:

1. ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` must be in the
   environment before the CPU backend is *initialized* (it is read at client
   creation, which is lazy — so setting it here, before any test touches
   jax, is early enough).

2. The interpreter's sitecustomize may register an experimental TPU-tunnel
   PJRT plugin and point ``jax_platforms`` at it via ``jax.config`` — which
   overrides the ``JAX_PLATFORMS`` env var.  ``jax.config.update`` after
   import is the reliable override; without it, test processes block on a
   remote TPU claim.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# Keep test compiles fast and deterministic.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
