"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Must run before any `import jax` so the backend sees the flags; pytest
imports conftest.py before collecting test modules, which guarantees that as
long as no test imports jax at module scope *in a file collected earlier* —
all our test files import through this root conftest first.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep test compiles fast and deterministic.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
