"""Reusable N-real-subprocess ``jax.distributed`` harness (ISSUE 5).

The fleet fault-domain layer can only be proven against REAL processes
— a thread can't be SIGKILL'd, and a mocked KV store can't lose its
coordinator — so the heartbeat tests and the multi-process soaks
(tests/test_fleet_multiproc.py, marker ``multiproc``) all spawn actual
interpreters running ``jax.distributed`` over localhost CPU.  This
module is the one copy of that machinery:

- ``FleetHarness(n)``: allocates a coordinator port and spawns ``n``
  processes — either ``spawn_script`` (a ``python -c`` body templated
  with ``{port}``/``{proc}``/``{n}``) or ``spawn_driver`` (the real
  ``scalable_agent_tpu.driver`` CLI with the distributed flags added).
  Per-process env/args overrides let a chaos spec arm a fault on
  exactly one peer.
- ``kill(i)`` / ``terminate(i)``: SIGKILL / SIGTERM one peer.
- ``wait_all(timeout)``: collect ``(returncode, output)`` per process,
  killing stragglers at the deadline so a hung assertion can't hang
  the suite.

Import pattern (tests/fakes has no package ``__init__``; the insert
must be SCOPED — this directory also holds fake simulator modules that
would shadow the real ones for any later ``find_spec``)::

    sys.path.insert(0, FAKES_DIR)
    try:
        import multiproc
    finally:
        sys.path.remove(FAKES_DIR)
"""

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("localhost", 0))
        return sock.getsockname()[1]


def base_env(devices_per_process: int = 1,
             extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """CPU-pinned subprocess environment (same forcing as conftest.py:
    the device-count flag must be set before backend init, and
    JAX_PLATFORMS must beat any sitecustomize TPU-tunnel pin)."""
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS=(f"--xla_force_host_platform_device_count="
                   f"{devices_per_process}"),
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    env.update(extra or {})
    return env


class FleetHarness:
    """N real ``jax.distributed`` subprocesses sharing one coordinator.

    Context-manager: exit kills every still-running process, so a
    failing assertion can never leak interpreters into the test
    session."""

    def __init__(self, n: int, devices_per_process: int = 1):
        self.n = n
        self.port = free_port()
        self.devices_per_process = devices_per_process
        self.procs: List[subprocess.Popen] = []

    # -- spawning ----------------------------------------------------------

    def spawn_script(self, body: str,
                     extra_env: Optional[Dict[str, str]] = None,
                     per_proc_env: Optional[Sequence[Optional[dict]]]
                     = None) -> "FleetHarness":
        """Launch ``python -c body`` once per process; ``body`` is
        ``str.format``-ed with ``port``/``proc``/``n``."""
        for proc_id in range(self.n):
            env = base_env(self.devices_per_process, extra_env)
            if per_proc_env and per_proc_env[proc_id]:
                env.update(per_proc_env[proc_id])
            self.procs.append(subprocess.Popen(
                [sys.executable, "-c",
                 body.format(port=self.port, proc=proc_id, n=self.n)],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        return self

    def spawn_driver(self, logdir: str, common_args: Sequence[str],
                     per_proc_args: Optional[Sequence[Sequence[str]]]
                     = None) -> "FleetHarness":
        """Launch the real driver CLI once per process with the
        distributed flags appended; ``per_proc_args[i]`` (e.g. a chaos
        spec) rides on exactly process i."""
        for proc_id in range(self.n):
            args = [
                sys.executable, "-m", "scalable_agent_tpu.driver",
                "--logdir", logdir,
                f"--distributed_coordinator=localhost:{self.port}",
                f"--distributed_num_processes={self.n}",
                f"--distributed_process_id={proc_id}",
            ] + list(common_args)
            if per_proc_args and per_proc_args[proc_id]:
                args += list(per_proc_args[proc_id])
            self.procs.append(subprocess.Popen(
                args, cwd=REPO, env=base_env(self.devices_per_process),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        return self

    # -- faults ------------------------------------------------------------

    def kill(self, index: int):
        """SIGKILL peer ``index`` — no handler, no flush, no goodbye."""
        self.procs[index].kill()

    def terminate(self, index: int):
        """SIGTERM peer ``index`` — the preemption-grace entry point."""
        self.procs[index].send_signal(signal.SIGTERM)

    # -- collection --------------------------------------------------------

    def wait_all(self, timeout_s: float) -> List[Tuple[int, str]]:
        """(returncode, combined output) per process, in spawn order.
        Stragglers past the shared deadline are SIGKILLed and reported
        with returncode -9 — the caller's assertion then names them."""
        deadline = time.monotonic() + timeout_s
        results: List[Optional[Tuple[int, str]]] = [None] * self.n
        for index, proc in enumerate(self.procs):
            remaining = max(0.1, deadline - time.monotonic())
            try:
                out = proc.communicate(timeout=remaining)[0]
            except subprocess.TimeoutExpired:
                proc.kill()
                out = proc.communicate(timeout=30)[0]
            results[index] = (proc.returncode, out or "")
        return results  # type: ignore[return-value]

    def wait_one(self, index: int, timeout_s: float) -> Tuple[int, str]:
        proc = self.procs[index]
        out = proc.communicate(timeout=timeout_s)[0]
        return proc.returncode, out or ""

    def poll(self, index: int) -> Optional[int]:
        return self.procs[index].poll()

    def __enter__(self) -> "FleetHarness":
        return self

    def __exit__(self, *exc):
        for proc in self.procs:
            if proc.poll() is None:
                proc.kill()
        for proc in self.procs:
            if proc.poll() is None:
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
        return False
