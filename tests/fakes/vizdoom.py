"""Fake ``vizdoom`` module for hermetic Doom-layer tests.

A deterministic stand-in for the VizDoom engine exposing exactly the API
surface scalable_agent_tpu.envs.doom consumes (DoomGame, ScreenResolution,
Mode).  Lives as a real module file (not a monkeypatch) so spawned env
worker subprocesses can import it when the tests put this directory on
PYTHONPATH.

Game model: episodes last EPISODE_TICS engine tics; frames are CHW uint8
filled with a (episode, tic) pattern; per-tic reward is (tic % 5) * 0.1;
game variables are deterministic functions of (name, tic) including a
declining HEALTH and growing FRAGCOUNT so the shaping/stats wrappers have
real deltas to chew on.  Multiplayer args (-host/-join) are recorded and
init always succeeds; set_action/advance_action implement the lockstep
path.
"""

import os
import re

import numpy as np

EPISODE_TICS = int(os.environ.get("FAKE_VIZDOOM_EPISODE_TICS", "64"))


class _State:
    def __init__(self, screen_buffer, game_variables, automap_buffer=None):
        self.screen_buffer = screen_buffer
        self.game_variables = game_variables
        self.automap_buffer = automap_buffer


class ScreenResolution:
    pass


for _res in ("160X120", "200X125", "200X150", "256X144", "320X240",
             "640X480", "800X600", "1280X720"):
    setattr(ScreenResolution, f"RES_{_res}", f"RES_{_res}")


class Mode:
    PLAYER = "PLAYER"
    ASYNC_PLAYER = "ASYNC_PLAYER"
    SPECTATOR = "SPECTATOR"
    ASYNC_SPECTATOR = "ASYNC_SPECTATOR"


class AutomapMode:
    NORMAL = "NORMAL"
    WHOLE = "WHOLE"
    OBJECTS = "OBJECTS"
    OBJECTS_WITH_SIZE = "OBJECTS_WITH_SIZE"


def _variable_value(name: str, tic: int) -> float:
    if name == "HEALTH":
        return max(0.0, 100.0 - tic)
    if name == "ARMOR":
        return float(tic % 7)
    if name == "FRAGCOUNT":
        return float(tic // 8)
    if name == "DEATHCOUNT":
        return float(tic // 16)
    if name == "HITCOUNT":
        return float(tic // 4)
    if name == "DAMAGECOUNT":
        return float(3 * (tic // 4))
    if name == "SELECTED_WEAPON":
        return 2.0
    if name == "SELECTED_WEAPON_AMMO":
        return max(0.0, 40.0 - tic // 2)
    if name == "ATTACK_READY":
        return float(tic % 2)
    if name == "PLAYER_NUM":
        return 1.0
    if name == "PLAYER_COUNT":
        return 2.0
    if name.startswith("PLAYER") and name.endswith("_FRAGCOUNT"):
        player = int(re.match(r"PLAYER(\d+)_", name).group(1))
        return float(tic // 8 - player)
    if name == "DEAD":
        return 0.0
    if name == "POSITION_X":
        return float((tic * 13) % 100)
    if name == "POSITION_Y":
        return float((tic * 29) % 50)
    return float(abs(hash(name)) % 10)


class DoomGame:
    def __init__(self):
        self.config_path = None
        self.variable_names = []
        self.args = []
        self.commands = []
        self.seed = 0
        self.width, self.height = 320, 240
        self.window_visible = None
        self.mode = None
        self.initialized = False
        self.closed = False
        self.tic = 0
        self.episode = 0
        self._last_reward = 0.0
        self._pending_action = None
        self.automap_enabled = False
        self.automap_mode = None
        self.automap_rotate = None
        self.automap_textures = None

    # -- config ------------------------------------------------------------

    def load_config(self, path):
        if not os.path.isfile(path):
            raise RuntimeError(f"config file {path} not found")
        self.config_path = path
        pattern = re.compile(r"available_game_variables\s*=\s*\{(.*)\}")
        with open(path) as f:
            for line in f:
                match = pattern.match(line.strip())
                if match:
                    self.variable_names = match.group(1).split()
                    break

    def set_screen_resolution(self, res):
        w, h = str(res).replace("RES_", "").split("X")
        self.width, self.height = int(w), int(h)

    def set_seed(self, seed):
        self.seed = int(seed)

    def set_window_visible(self, visible):
        self.window_visible = bool(visible)

    def set_mode(self, mode):
        self.mode = mode

    def add_game_args(self, args):
        self.args.append(args)

    def init(self):
        self.initialized = True
        self.tic = 0

    # -- episode -----------------------------------------------------------

    def new_episode(self, demo_path=None):
        self.tic = 0
        self.episode += 1
        self.demo_path = demo_path

    def is_episode_finished(self):
        return self.tic >= EPISODE_TICS

    def _frame(self):
        base = (self.episode * 31 + self.tic * 7) % 251
        frame = np.full((3, self.height, self.width), base, np.uint8)
        frame[0, 0, 0] = self.tic % 256
        return frame

    def get_state(self):
        if self.is_episode_finished():
            return None
        variables = [_variable_value(name, self.tic)
                     for name in self.variable_names]
        automap = self._frame() if self.automap_enabled else None
        return _State(self._frame(), variables, automap)

    def set_automap_buffer_enabled(self, enabled):
        self.automap_enabled = bool(enabled)

    def set_automap_mode(self, mode):
        self.automap_mode = mode

    def set_automap_rotate(self, rotate):
        self.automap_rotate = bool(rotate)

    def set_automap_render_textures(self, textures):
        self.automap_textures = bool(textures)

    # -- stepping ----------------------------------------------------------

    def _advance(self, tics):
        reward = 0.0
        for _ in range(tics):
            if self.is_episode_finished():
                break
            self.tic += 1
            reward += (self.tic % 5) * 0.1
        self._last_reward = reward
        return reward

    def make_action(self, buttons, skip=1):
        assert isinstance(buttons, (list, tuple)), buttons
        assert all(isinstance(b, (int, float)) for b in buttons), buttons
        self._pending_action = list(buttons)
        return self._advance(skip)

    def set_action(self, buttons):
        self._pending_action = list(buttons)

    def advance_action(self, tics=1, update_state=True):
        self._advance(tics)

    def get_episode_time(self):
        return self.tic

    def get_total_reward(self):
        # fake: cumulative reward == 0.1 * tic count this episode
        return 0.1 * self.tic

    def get_last_reward(self):
        return self._last_reward

    def send_game_command(self, command):
        self.commands.append(command)

    def close(self):
        self.closed = True
        self.initialized = False
