"""Fake ``deepmind_lab`` module for hermetic suite-eval tests.

Real-file twin of the in-process FakeLab used by tests/test_env_adapters
so spawned env worker subprocesses can import it when this directory is
on sys.path.  Deterministic short episodes with a per-level reward bias
so different suite levels produce different mean returns.
"""

import os

import numpy as np

EPISODE_STEPS = int(os.environ.get("FAKE_DMLAB_EPISODE_STEPS", "6"))


def set_runfiles_path(path):
    pass


class Lab:
    def __init__(self, level, observations, config, renderer,
                 level_cache=None):
        self.level = level
        self.observation_names = list(observations)
        self.config = config
        self.renderer = renderer
        self.level_cache = level_cache
        self.width = int(config["width"])
        self.height = int(config["height"])
        self._steps = 0
        self._seed = 0
        # deterministic per-level flavor
        self._bias = (sum(level.encode()) % 7) * 0.1

    def reset(self, seed=None):
        self._seed = seed or 0
        self._steps = 0

    def observations(self):
        obs = {"RGB_INTERLEAVED": np.full(
            (self.height, self.width, 3),
            (self._steps * 11 + self._seed) % 251, np.uint8)}
        if "INSTR" in self.observation_names:
            obs["INSTR"] = b""
        return obs

    def step(self, action, num_steps=1):
        self._steps += 1
        return float(num_steps) * (0.25 + self._bias)

    def is_running(self):
        return self._steps < EPISODE_STEPS

    def close(self):
        pass
