"""Kernel roofline ledger (obs/kernels.py): profiler trace × HLO cost
model → kernels.json → report.

The acceptance loop on the CPU rig: a traced run's per-kernel FLOPs sum
to the ledger-MFU numerator (XLA's cost-analysis total over the shared
``PEAK_FLOPS`` denominator), ``kernels.json`` is written by a traced
driver run, and ``python -m scalable_agent_tpu.obs.report --json``
names the dominant kernel — plus the report's bench-artifact section
naming ``conv0_gradw`` from the committed r04/r05 readings
automatically.
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalable_agent_tpu.obs import kernels as kernels_lib

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _compiled_conv_dot():
    def f(x, w, m):
        y = jax.lax.conv_general_dilated(
            x, w, (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return (jnp.tanh(y).reshape(x.shape[0], -1)[:, :64] @ m).sum()

    x = jnp.ones((8, 32, 32, 3))
    w = jnp.ones((5, 5, 3, 16))
    m = jnp.ones((64, 32))
    compiled = jax.jit(f).lower(x, w, m).compile()
    return compiled, (x, w, m)


class TestHloCostModel:
    def test_dot_flops_exact(self):
        hlo = """
ENTRY %main (a: f32[128,64], b: f32[64,32]) -> f32[128,32] {
  %a = f32[128,64]{1,0} parameter(0)
  %b = f32[64,32]{1,0} parameter(1)
  ROOT %dot.1 = f32[128,32]{1,0} dot(f32[128,64]{1,0} %a, f32[64,32]{1,0} %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
        costs = kernels_lib.parse_hlo_kernel_costs(hlo)
        assert costs["dot.1"]["flops_est"] == 2 * 128 * 32 * 64
        # bytes: both operands + the result, f32.
        assert costs["dot.1"]["bytes"] == 4 * (128 * 64 + 64 * 32
                                               + 128 * 32)
        assert costs["a"]["flops_est"] == 0.0  # parameters are free

    def test_fusion_sums_called_computation(self):
        hlo = """
%fused (p: f32[1024]) -> f32[1024] {
  %p = f32[1024]{0} parameter(0)
  %t = f32[1024]{0} tanh(f32[1024]{0} %p)
  ROOT %m = f32[1024]{0} multiply(f32[1024]{0} %t, f32[1024]{0} %t)
}
ENTRY %main (x: f32[1024]) -> f32[1024] {
  %x = f32[1024]{0} parameter(0)
  ROOT %my_fusion = f32[1024]{0} fusion(f32[1024]{0} %x), kind=kLoop, calls=%fused
}
"""
        costs = kernels_lib.parse_hlo_kernel_costs(hlo)
        assert costs["my_fusion"]["flops_est"] == 2 * 1024
        # Fusion bytes are the kernel-boundary traffic, not the
        # internal temporaries.
        assert costs["my_fusion"]["bytes"] == 4 * 2 * 1024

    def test_scope_attribution_from_op_name_metadata(self):
        """ISSUE 15: jax.named_scope markers (runtime/ingraph.py wraps
        env_step / actor_inference / learner_update) surface through
        the HLO op_name metadata as a per-instruction ``scope`` and an
        aggregate ``scope_time_shares`` — the env-vs-learner split the
        report names inside a device_bound verdict."""
        hlo = """
ENTRY %main (a: f32[128,64], b: f32[64,32]) -> f32[128,32] {
  %a = f32[128,64]{1,0} parameter(0)
  %b = f32[64,32]{1,0} parameter(1)
  %env.1 = f32[128,64]{1,0} tanh(f32[128,64]{1,0} %a), metadata={op_name="jit(_fused)/while/body/env_step/tanh"}
  %infer.1 = f32[128,64]{1,0} negate(f32[128,64]{1,0} %env.1), metadata={op_name="jit(_fused)/while/body/actor_inference/neg"}
  %upd.1 = f32[64,32]{1,0} exponential(f32[64,32]{1,0} %b), metadata={op_name="jit(_fused)/learner_update/exp"}
  ROOT %dot.1 = f32[128,32]{1,0} dot(f32[128,64]{1,0} %infer.1, f32[64,32]{1,0} %upd.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
        costs = kernels_lib.parse_hlo_kernel_costs(hlo)
        assert costs["env.1"]["scope"] == "env"
        assert costs["infer.1"]["scope"] == "inference"
        assert costs["upd.1"]["scope"] == "learner"
        assert costs["dot.1"]["scope"] is None

        events = {
            "env.1": {"time_us": 30.0, "calls": 1.0},
            "infer.1": {"time_us": 20.0, "calls": 1.0},
            "upd.1": {"time_us": 40.0, "calls": 1.0},
            "dot.1": {"time_us": 10.0, "calls": 1.0},
        }
        table = kernels_lib.build_kernel_table(events, costs,
                                               peak_flops=1e12)
        shares = table["scope_time_shares"]
        assert shares["env"] == pytest.approx(0.30)
        assert shares["inference"] == pytest.approx(0.20)
        assert shares["learner"] == pytest.approx(0.40)
        assert shares["unattributed"] == pytest.approx(0.10)
        by_name = {row["name"]: row for row in table["kernels"]}
        assert by_name["env.1"]["scope"] == "env"

    def test_pallas_gradw_custom_call_flops(self):
        """ISSUE 18: a pallas_call lowers to a custom-call XLA cannot
        see inside, so the named grad-W kernel gets an explicit cost —
        2 * N*OH*OW * rows * F off the operand/result shapes — instead
        of the one-flop-per-element floor (which would misprice the MXU
        matmul by ~3 orders of magnitude and hide it from the
        worst-kernel verdict)."""
        hlo = """
ENTRY %main (xs: bf16[256,19,25,48], g: bf16[256,18,24,32]) -> f32[768,32] {
  %xs = bf16[256,19,25,48]{3,2,1,0} parameter(0)
  %g = bf16[256,18,24,32]{3,2,1,0} parameter(1)
  ROOT %cc.1 = f32[768,32]{1,0} custom-call(bf16[256,19,25,48]{3,2,1,0} %xs, bf16[256,18,24,32]{3,2,1,0} %g), custom_call_target="tpu_custom_call", metadata={op_name="jit(update)/pallas_conv0_gradw/pallas_call"}
}
"""
        costs = kernels_lib.parse_hlo_kernel_costs(hlo)
        # The g operand is the 4-d input whose trailing dim matches the
        # result's feature dim; contraction length is its N*OH*OW.
        assert costs["cc.1"]["flops_est"] == pytest.approx(
            2 * (256 * 18 * 24) * 768 * 32)
        assert costs["cc.1"]["op"] == "custom-call"

    def test_unrecognized_custom_call_keeps_elementwise_floor(self):
        """A custom-call without a registered Pallas cost entry must
        stay on the explicit one-flop-per-element floor, not crash or
        inherit another kernel's formula."""
        hlo = """
ENTRY %main (a: f32[64,32]) -> f32[64,32] {
  %a = f32[64,32]{1,0} parameter(0)
  ROOT %cc.9 = f32[64,32]{1,0} custom-call(f32[64,32]{1,0} %a), custom_call_target="tpu_custom_call", metadata={op_name="jit(update)/some_other_kernel/pallas_call"}
}
"""
        costs = kernels_lib.parse_hlo_kernel_costs(hlo)
        assert costs["cc.9"]["flops_est"] == 64 * 32

    def test_gradw_marker_matches_ops_contract(self):
        """The cost-model marker string and ops/conv_pallas.py's
        GRADW_KERNEL_NAME are the same contract — kernels.py is
        jax-free so it cannot import the op; this pins the two sides
        together."""
        from scalable_agent_tpu.ops import conv_pallas

        assert (kernels_lib._PALLAS_GRADW_MARKER
                == conv_pallas.GRADW_KERNEL_NAME)

    def test_real_compiled_module_parses_and_names_ops(self):
        compiled, _ = _compiled_conv_dot()
        costs = kernels_lib.parse_hlo_kernel_costs(compiled.as_text())
        conv = [n for n, c in costs.items() if c["op"] == "convolution"]
        dots = [n for n, c in costs.items() if c["op"] == "dot"]
        assert conv and dots
        # Conv flops: 2 * out_elems * kernel_taps_per_output.
        (conv_name, ) = conv
        assert costs[conv_name]["flops_est"] == pytest.approx(
            2 * (8 * 16 * 16 * 16) * (5 * 5 * 3))


class TestTraceJoin:
    def test_harvest_roundtrip(self, tmp_path, monkeypatch):
        """Profile a compiled program, harvest, and verify the
        acceptance identity: per-kernel FLOPs sum to the MFU numerator
        handed in (the XLA cost-analysis total)."""
        compiled, args = _compiled_conv_dot()
        compiled(*args)  # warm
        profile_dir = str(tmp_path / "prof")
        executions = 4
        with jax.profiler.trace(profile_dir):
            for _ in range(executions):
                out = compiled(*args)
            jax.block_until_ready(out)

        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops_total = float(cost["flops"])
        from scalable_agent_tpu.obs import MetricsRegistry

        registry = MetricsRegistry()
        table = kernels_lib.harvest(
            profile_dir, compiled.as_text(), flops_total,
            peak_flops=1e12, logdir=str(tmp_path / "run"),
            registry=registry, executions=executions)
        assert table is not None and table["kernels"], table

        # THE identity: per-kernel FLOPs sum to the ledger-MFU
        # numerator (normalized attribution of XLA's own total).
        assert sum(row["flops"] for row in table["kernels"]) \
            == pytest.approx(flops_total, rel=1e-6)
        assert table["flops_total"] == flops_total

        # kernels.json persisted and re-readable.
        path = os.path.join(str(tmp_path / "run"), "kernels.json")
        assert os.path.exists(path)
        persisted = json.load(open(path))
        assert persisted["dominant_kernel"] == table["dominant_kernel"]

        # Roofline MFU is populated against the synthetic peak and the
        # rows aggregate real calls from the window.
        dominant = table["kernels"][0]
        assert dominant["calls"] >= executions
        assert 0 < dominant["mfu"] <= 1.0 or dominant["mfu"] >= 0

        # Registry gauges for the verdict + the stall hand-off.
        snap = registry.snapshot()
        assert "kernel/matched_time_frac" in snap
        assert kernels_lib.last_dominant(registry)[0] \
            == table["dominant_kernel"]
        assert kernels_lib.last_dominant(MetricsRegistry()) is None

    def test_harvest_without_traces_returns_none(self, tmp_path):
        assert kernels_lib.harvest(
            str(tmp_path / "nothing"), "", 0.0, None, None) is None

    def test_trace_events_filter_by_hlo_module(self, tmp_path):
        """XLA instruction names are unique only per module: an event
        annotated with ANOTHER module's name (a concurrently-running
        actor_step, say) must not be joined to the update's same-named
        instruction; unannotated events pass through."""
        path = str(tmp_path / "x.trace.json")
        events = [
            {"ph": "X", "name": "fusion.1", "dur": 10.0,
             "args": {"hlo_module": "jit_update"}},
            {"ph": "X", "name": "fusion.1", "dur": 999.0,
             "args": {"hlo_module": "jit_actor_step"}},
            {"ph": "X", "name": "fusion.2", "dur": 5.0},  # unannotated
        ]
        with open(path, "w") as f:
            json.dump({"traceEvents": events}, f)
        out = kernels_lib.load_trace_kernel_events(
            path, module="jit_update")
        assert out["fusion.1"] == {"time_us": 10.0, "calls": 1.0}
        assert out["fusion.2"] == {"time_us": 5.0, "calls": 1.0}
        # No filter: everything aggregates by name (legacy behavior).
        both = kernels_lib.load_trace_kernel_events(path)
        assert both["fusion.1"]["time_us"] == pytest.approx(1009.0)
        # The module name harvest() derives comes off the HLO header.
        assert kernels_lib.hlo_module_name(
            "HloModule jit_update, is_scheduled=true\n") == "jit_update"


class TestReportKernels:
    def _write_minimal_prom(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        with open(os.path.join(logdir, "metrics.prom"), "w") as f:
            f.write("# TYPE impala_ledger_mfu gauge\n"
                    "impala_ledger_mfu 0.1\n")

    def test_report_json_names_dominant_kernel(self, tmp_path, capsys):
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        self._write_minimal_prom(logdir)
        kernels_lib.write_kernels_json(logdir, {
            "schema_version": 1,
            "flops_total": 1e9,
            "matched_time_frac": 0.9,
            "kernels": [
                {"name": "loss_grad_fusion", "time_us": 900.0,
                 "time_share": 0.9, "calls": 5, "flops": 9e8,
                 "intensity": 12.0, "mfu": 0.11},
                {"name": "optimizer_fusion", "time_us": 100.0,
                 "time_share": 0.1, "calls": 5, "flops": 1e8,
                 "intensity": 3.0, "mfu": 0.55},
            ],
            "worst_kernel": "loss_grad_fusion",
            "worst_kernel_mfu": 0.11,
            "dominant_kernel": "loss_grad_fusion",
            "dominant_time_share": 0.9,
        })
        assert report.main(["--json", logdir]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["kernels"]["dominant"] == "loss_grad_fusion"
        assert payload["kernels"]["worst"] == "loss_grad_fusion"
        assert payload["kernels"]["rows"][0]["mfu"] == 0.11

        # The text rendering carries the same verdict.
        assert report.main([logdir]) == 0
        out = capsys.readouterr().out
        assert "worst kernels (this run's profile window)" in out
        assert "loss_grad_fusion" in out
        assert "worst kernel: loss_grad_fusion" in out

    def test_report_names_conv0_gradw_from_bench_artifact(
            self, tmp_path, capsys):
        """The committed BENCH_r05 artifact carries the hand-measured
        kernel rooflines; the report must surface them automatically
        and name conv0_gradw (0.107 MFU) as the worst kernel."""
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        self._write_minimal_prom(logdir)
        payload = report.build_report(logdir, bench_dir=REPO_ROOT)
        bench_kernels = payload["bench_kernels"]
        assert bench_kernels is not None
        assert bench_kernels["worst"] == "conv0_gradw"
        assert bench_kernels["worst_mfu"] == pytest.approx(0.107)
        names = {row["name"] for row in bench_kernels["rows"]}
        assert "conv0_gradw" in names

        assert report.main([logdir, "--bench_dir", REPO_ROOT]) == 0
        out = capsys.readouterr().out
        assert "worst kernels (newest bench artifact)" in out
        assert "worst kernel: conv0_gradw" in out

        assert report.main(["--json", logdir,
                            "--bench_dir", REPO_ROOT]) == 0
        machine = json.loads(capsys.readouterr().out)
        assert machine["bench_kernels"]["worst"] == "conv0_gradw"

    def test_bench_kernels_absent_outside_a_checkout(self, tmp_path):
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        self._write_minimal_prom(logdir)
        payload = report.build_report(
            logdir, bench_dir=str(tmp_path / "empty"))
        assert payload["bench_kernels"] is None


def test_traced_driver_run_writes_kernel_ledger(tmp_path, monkeypatch,
                                                capsys):
    """Tier-1 acceptance: a --profile_dir driver run on the CPU rig
    writes kernels.json, publishes kernel/* gauges into the prom
    snapshot, and the report CLI names the dominant kernel from it."""
    from scalable_agent_tpu.config import Config
    from scalable_agent_tpu.driver import train as run_train
    from scalable_agent_tpu.obs import report

    monkeypatch.setenv("SCALABLE_AGENT_LEDGER_MFU_PEAK", "1e12")
    config = Config(
        mode="train",
        logdir=str(tmp_path / "run"),
        level_name="fake_small",
        num_actors=4,
        batch_size=2,
        unroll_length=4,
        num_action_repeats=1,
        total_environment_frames=24,  # 3 updates of 8 frames
        height=16,
        width=16,
        num_env_workers_per_group=2,
        compute_dtype="float32",
        checkpoint_interval_s=1e9,
        log_interval_s=0.0,
        profile_dir=str(tmp_path / "profile"),
        profile_start_update=1,
        profile_num_updates=1,
        seed=5,
    )
    metrics = run_train(config)
    assert metrics["env_frames"] == 24

    # The profile window left a device trace and the harvest joined it.
    kernels_path = os.path.join(config.logdir, "kernels.json")
    assert os.path.exists(kernels_path), glob.glob(
        os.path.join(config.logdir, "*"))
    table = json.load(open(kernels_path))
    assert table["kernels"], table
    assert table["dominant_kernel"]
    assert table["flops_total"] > 0
    assert sum(row["flops"] for row in table["kernels"]) \
        == pytest.approx(table["flops_total"], rel=1e-6)

    # kernel/* gauges rode the prom snapshot.
    prom = open(os.path.join(config.logdir, "metrics.prom")).read()
    assert "impala_kernel_matched_time_frac" in prom
    assert "impala_kernel_dominant_time_share" in prom

    # The report names the dominant kernel, machine-readably.
    assert report.main(["--json", config.logdir]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["kernels"]["dominant"] == table["dominant_kernel"]
