"""ISSUE 6 acceptance: elastic membership against a REAL fleet.

One end-to-end soak over the real supervisor
(``python -m scalable_agent_tpu.runtime.elastic``) driving a real
3-process ``jax.distributed`` training fleet on localhost CPU:

1. epoch 0 trains at N=3 and lands a durable checkpoint;
2. one worker is SIGKILLed — the survivors exit 72, the supervisor
   reshards, and epoch 1 continues as a 2-process fleet resuming from
   the newest verified checkpoint (MTTR recorded);
3. the lost slot rejoins (marker file) — the supervisor drains the
   fleet through the grace protocol at a checkpoint boundary and
   epoch 2 runs at N=3 again;
4. the supervisor is SIGTERMed — the fleet drains to one final
   coordinated verified checkpoint and everything exits 0 — and the
   final checkpoint's ``env_frames`` is EXACTLY ``step x
   frames_per_update``: nothing double-counted across two reshards
   and two restores.

Markers ``multiproc`` + ``slow``: excluded from tier-1 (the soak
stands up three real fleets back to back).
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = [pytest.mark.multiproc, pytest.mark.slow]

FAKES_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "fakes")
# Scoped import (see tests/test_fleet_multiproc.py): the fakes dir also
# shadows real simulator packages for find_spec.
sys.path.insert(0, FAKES_DIR)
try:
    import multiproc  # noqa: E402
finally:
    sys.path.remove(FAKES_DIR)

N = 3
FPU = 6 * 3 * 1  # batch 6 x unroll 3 x repeats 1
SUPERVISOR_ARGS = [
    "--mode=train", "--level_name=fake_small",
    "--num_actors=4", "--batch_size=6", "--unroll_length=3",
    "--num_action_repeats=1", "--height=16", "--width=16",
    "--num_env_workers_per_group=1", "--compute_dtype=float32",
    "--log_interval_s=0.2", "--seed=3",
    "--checkpoint_interval_s=1.0",
    "--peer_timeout_s=6", "--preemption_grace_s=45",
    "--total_environment_frames=1000000",
    f"--distributed_num_processes={N}",
    # Rejoin is marker-gated: the test controls WHEN the lost host
    # "comes back".
    "--elastic_rejoin_delay_s=1000000",
    "--elastic_restart_budget=4",
]


def _events(logdir):
    path = os.path.join(logdir, "fleet_epochs.jsonl")
    if not os.path.exists(path):
        return []
    out = []
    for line in open(path).read().splitlines():
        if line:
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass  # torn tail mid-append
    return out


def _retained_steps(logdir):
    steps = []
    for name in glob.glob(os.path.join(logdir, "checkpoints", "*")):
        base = os.path.basename(name)
        if base.isdigit():
            steps.append(int(base))
    return sorted(steps)


def _wait_for(predicate, supervisor, deadline_s, what):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        if supervisor.poll() is not None:
            pytest.fail(
                f"supervisor exited early ({supervisor.returncode}) "
                f"waiting for {what}")
        time.sleep(0.5)
    pytest.fail(f"no {what} within {deadline_s:.0f}s")


def test_sigkill_reshard_then_rejoin_frame_exact(tmp_path):
    logdir = str(tmp_path / "run")
    env = multiproc.base_env(devices_per_process=1)
    # Worker processes INHERIT the supervisor's stdout: it must be a
    # file, not a pipe nobody drains (a full pipe buffer would block
    # every worker's logging mid-training).
    console_path = str(tmp_path / "console.log")
    console = open(console_path, "w")
    supervisor = subprocess.Popen(
        [sys.executable, "-m", "scalable_agent_tpu.runtime.elastic",
         "--logdir", logdir] + SUPERVISOR_ARGS,
        cwd=multiproc.REPO, env=env, stdout=console,
        stderr=subprocess.STDOUT)

    def console_tail():
        try:
            return open(console_path).read()[-4000:]
        except OSError:
            return "<no console output>"
    try:
        # -- epoch 0: N=3 up, first durable checkpoint.
        launch0 = _wait_for(
            lambda: next((e for e in _events(logdir)
                          if e["event"] == "launch"
                          and e["epoch"] == 0), None),
            supervisor, 120, "epoch 0 launch record")
        assert launch0["num_processes"] == N
        assert launch0["slots"] == [0, 1, 2]
        _wait_for(lambda: len(_retained_steps(logdir)) >= 1,
                  supervisor, 300, "first durable checkpoint")
        pre_kill_latest = _retained_steps(logdir)[-1]

        # -- kill one NON-coordinator worker's host.
        os.kill(launch0["pids"][1], signal.SIGKILL)

        # -- epoch 1: the supervisor reshards to N-1.
        launch1 = _wait_for(
            lambda: next((e for e in _events(logdir)
                          if e["event"] == "launch"
                          and e["epoch"] == 1), None),
            supervisor, 180, "epoch 1 (resharded) launch record")
        assert launch1["num_processes"] == N - 1
        assert launch1["slots"] == [0, 2]
        exit0 = next(e for e in _events(logdir)
                     if e["event"] == "exit" and e["epoch"] == 0)
        assert exit0["outcome"] == "reshard"
        assert exit0["lost_slots"] == [1]
        # The survivors' membership verdict named the lost peer.
        # The survivors' membership verdict rode into the exit record
        # (the FILE is transient — the supervisor consumes it and
        # clears it before the next launch).  WHICH kind lands is a
        # race three ways bounded: the monitor's heartbeat verdict
        # (peer_lost), the coordinator-death shape (kv_unreachable),
        # or the aborted collective's exception unwinding first
        # (collective_error via note_fatal_error — gloo fails fast on
        # a reset connection, and jax's client fatal can SIGABRT the
        # survivor mid-teardown).
        assert exit0["verdict_kind"] in (
            "peer_lost", "kv_unreachable", "collective_error")

        # -- the 2-process fleet makes VERIFIED progress + MTTR lands.
        _wait_for(
            lambda: (_retained_steps(logdir)
                     and _retained_steps(logdir)[-1] > pre_kill_latest),
            supervisor, 300, "post-reshard checkpoint progress")
        mttr = _wait_for(
            lambda: next((e for e in _events(logdir)
                          if e["event"] == "mttr"), None),
            supervisor, 120, "MTTR record")
        assert 0.0 < mttr["mttr_s"] < 300.0

        # -- rejoin: the lost host comes back; scale-up at the next
        #    checkpoint boundary (the coordinated grace drain).
        open(os.path.join(logdir, "rejoin.1"), "w").write("back")
        launch2 = _wait_for(
            lambda: next((e for e in _events(logdir)
                          if e["event"] == "launch"
                          and e["epoch"] == 2), None),
            supervisor, 300, "epoch 2 (rejoined) launch record")
        assert launch2["num_processes"] == N
        assert launch2["slots"] == [0, 1, 2]
        exit1 = next(e for e in _events(logdir)
                     if e["event"] == "exit" and e["epoch"] == 1)
        assert exit1["outcome"] == "scale_up"
        assert exit1["codes"] == [0, 0]  # graceful drain, not a crash
        boundary_step = _retained_steps(logdir)[-1]

        # -- the full-size fleet makes progress again, then the
        #    supervisor is preempted: drain everything, exit 0.
        _wait_for(
            lambda: (_retained_steps(logdir)
                     and _retained_steps(logdir)[-1] > boundary_step),
            supervisor, 300, "post-rejoin checkpoint progress")
        supervisor.send_signal(signal.SIGTERM)
        supervisor.wait(timeout=240)
        assert supervisor.returncode == 0, console_tail()
        exit2 = next(e for e in _events(logdir)
                     if e["event"] == "exit" and e["epoch"] == 2)
        assert exit2["outcome"] == "shutdown"
        assert exit2["codes"] == [0, 0, 0]
    finally:
        if supervisor.poll() is None:
            supervisor.kill()
            supervisor.wait(timeout=60)
        console.close()
        # The supervisor's own children die with it on the kill path:
        # any straggler worker pid recorded in the epoch log is
        # hard-killed so a failing assertion can't leak interpreters.
        for event in _events(logdir):
            if event["event"] == "launch":
                for pid in event.get("pids") or []:
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except (OSError, TypeError):
                        pass

    # -- membership history is one machine-readable timeline.
    launches = [e for e in _events(logdir) if e["event"] == "launch"]
    assert [e["num_processes"] for e in launches] == [3, 2, 3]
    prom = open(os.path.join(logdir, "metrics.supervisor.prom")).read()
    assert "impala_fleet_resize_total 2.0" in prom
    assert "impala_fleet_mttr_s" in prom

    # -- frame-exact accounting across BOTH reshards: the newest
    #    verified checkpoint's on-device counter is exactly
    #    updates x frames_per_update.
    steps = _retained_steps(logdir)
    assert steps, "no checkpoint survived the run"
    latest = steps[-1]
    assert os.path.exists(os.path.join(
        logdir, "checkpoints", "manifests", f"{latest}.json"))
    import jax

    jax.config.update("jax_platforms", "cpu")
    from scalable_agent_tpu.runtime.checkpoint import CheckpointManager

    ckpt = CheckpointManager(logdir)
    try:
        step, restored = ckpt.restore()
        assert step == latest
        assert float(np.asarray(restored["env_frames"])) == step * FPU
        # The N-process checkpoint restores here at 1 process with its
        # manifest verifying — the N±1 restore contract, natively.
        manifest_topology = ckpt.saved_topology(step)
        assert manifest_topology["num_processes"] in (N, N - 1)
    finally:
        ckpt.close()
