"""Full-suite eval mode: batched multi-level test() with suite scores.

(VERDICT r2 item 7: the reference loops all level names and computes
capped/uncapped human-normalized suite scores, experiment.py:675-708,
716-717; done-criterion = suite score emitted for the dmlab30 list on
fakes.)
"""

import json
import os
import sys

import numpy as np
import pytest

from scalable_agent_tpu.config import Config
from scalable_agent_tpu.envs import dmlab30

FAKES_DIR = os.path.join(os.path.dirname(__file__), "fakes")


@pytest.fixture(scope="module", autouse=True)
def fake_lab():
    sys.path.insert(0, FAKES_DIR)
    sys.modules.pop("deepmind_lab", None)
    yield
    sys.path.remove(FAKES_DIR)
    sys.modules.pop("deepmind_lab", None)


@pytest.fixture(scope="module")
def trained_logdir(tmp_path_factory, fake_lab):
    """A tiny checkpointed train run on one dmlab level (fake sim)."""
    from scalable_agent_tpu.driver import train

    logdir = str(tmp_path_factory.mktemp("suite_eval") / "run")
    config = Config(
        mode="train",
        logdir=logdir,
        level_name="dmlab_explore_goal_locations_small",
        num_actors=2, batch_size=2, unroll_length=3,
        num_action_repeats=2, num_env_workers_per_group=1,
        total_environment_frames=2 * 2 * 3 * 2,  # 2 updates
        compute_dtype="float32",
        checkpoint_interval_s=1e9,
    )
    train(config)
    return logdir


@pytest.mark.slow
def test_suite_eval_emits_scores(trained_logdir):
    from scalable_agent_tpu.driver import test as run_test

    config = Config(
        mode="test",
        logdir=trained_logdir,
        level_name="dmlab30",
        num_action_repeats=2,
        test_num_episodes=2,
        test_batch_size=2,
        test_num_workers=1,
        width=96, height=72,
    )
    level_returns = run_test(config)

    # every suite test level evaluated with the requested episode count
    assert len(level_returns) == len(dmlab30.TEST_LEVELS)
    for name, returns in level_returns.items():
        assert name.startswith("dmlab_")
        assert len(returns) == 2, name

    scores_path = os.path.join(trained_logdir, "eval_scores.json")
    assert os.path.exists(scores_path)
    with open(scores_path) as f:
        scores = json.load(f)
    assert np.isfinite(scores["human_normalized_no_cap"])
    assert np.isfinite(scores["human_normalized_cap_100"])
    assert scores["human_normalized_cap_100"] <= scores[
        "human_normalized_no_cap"] + 1e-9
    assert len(scores["mean_returns"]) == 30


def test_single_level_eval_still_works(trained_logdir):
    from scalable_agent_tpu.driver import test as run_test

    config = Config(
        mode="test",
        logdir=trained_logdir,
        level_name="dmlab_explore_goal_locations_small",
        num_action_repeats=2,
        test_num_episodes=3,
        test_batch_size=2,
        test_num_workers=1,
        width=96, height=72,
    )
    level_returns = run_test(config)
    returns = level_returns["dmlab_explore_goal_locations_small"]
    assert len(returns) == 3
    assert all(np.isfinite(r) for r in returns)
