"""ISSUE 16: the run-health plane (obs/health.py) + its consoles.

Golden synthetic streams per detector kind (step change, slow drift,
flapping under cooldown, warm-up gating, baseline-primed immediate
fire), the anomaly-record schema, the profiling-window budget/cooldown
arbitration, the fleet fold rules for ``health/*`` series, the
``obs.watch`` console on a synthetic logdir, the exit-2 contract of
both jax-free CLIs, the ``/anomalies`` + ``/health`` HTTP routes — and
the tier-1 acceptance run: a CPU driver run under
``--chaos_spec='throughput_sag@...'`` must detect the sag, pin + dump
the flight recorder, and auto-profile exactly one window whose
harvested ``kernels.<anomaly_id>.json`` lands back in the record,
while the identical run without chaos stays anomaly-free.
"""

import glob
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from scalable_agent_tpu.obs import aggregate
from scalable_agent_tpu.obs.exporters import MetricsHTTPServer
from scalable_agent_tpu.obs.health import (
    ANOMALIES_JSONL,
    DetectorSpec,
    HealthMonitor,
    default_detectors,
    read_anomalies,
)
from scalable_agent_tpu.obs.registry import MetricsRegistry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


class _StubRecorder:
    """Flight-recorder stand-in: records the pin/dump protocol without
    touching the process-global ring."""

    def __init__(self):
        self.reason_pin = None
        self.last_dump_reason = None
        self.events = []

    def record(self, kind, name, payload=None):
        self.events.append((kind, name, payload))

    def dump_all(self, reason):
        if self.reason_pin is not None:
            reason = self.reason_pin
        self.last_dump_reason = reason


def _monitor(detectors, clock=None, logdir=None, **kwargs):
    kwargs.setdefault("registry", MetricsRegistry())
    kwargs.setdefault("recorder", _StubRecorder())
    return HealthMonitor(detectors, logdir=logdir,
                         clock=clock or _FakeClock(), **kwargs)


class TestDetectorGoldens:
    def test_step_change_trips_ewma_after_warmup(self):
        clock = _FakeClock()
        mon = _monitor([DetectorSpec(name="fps", metric="m", warmup=3)],
                       clock=clock)
        for _ in range(4):
            clock.advance(10.0)
            assert mon.step({"m": 1000.0}) == []
        clock.advance(10.0)
        fired = mon.step({"m": 250.0}, update=5)
        assert len(fired) == 1
        record = fired[0]
        assert record["detector"] == "fps"
        assert record["observed"] == 250.0
        assert record["baseline"] == pytest.approx(1000.0)
        assert record["rel"] >= 0.6
        assert record["primed"] is False

    def test_warmup_gates_an_early_drop(self):
        clock = _FakeClock()
        mon = _monitor([DetectorSpec(name="fps", metric="m", warmup=3)],
                       clock=clock)
        clock.advance(10.0)
        assert mon.step({"m": 1000.0}) == []
        clock.advance(10.0)
        # A 10x drop on sample 2 (compile-dominated interval in a real
        # run) must NOT fire — the detector is still warming up.
        assert mon.step({"m": 100.0}) == []

    def test_slow_drift_trips_cusum_but_not_ewma(self):
        """A +4%-per-interval loss creep: each interval's z stays far
        under the spike threshold (no single-step anomaly exists), but
        the one-sided CUSUM accumulates it into a drift verdict."""
        clock = _FakeClock()
        specs = [
            DetectorSpec(name="spike", metric="loss", kind="ewma",
                         direction="high", warmup=4, z_threshold=5.0,
                         rel_threshold=None, min_rel=0.0,
                         sigma_floor_rel=0.05),
            DetectorSpec(name="drift", metric="loss", kind="cusum",
                         direction="high", warmup=4,
                         sigma_floor_rel=0.05),
        ]
        mon = _monitor(specs, clock=clock, cooldown_s=0.0)
        fired_names = []
        value = 1.0
        for i in range(30):
            clock.advance(10.0)
            if i >= 5:
                value += 0.04
            fired_names += [r["detector"]
                            for r in mon.step({"loss": value})]
        assert "drift" in fired_names
        assert "spike" not in fired_names

    def test_flapping_is_suppressed_by_cooldown(self):
        clock = _FakeClock()
        reg = MetricsRegistry()
        mon = _monitor([DetectorSpec(name="fps", metric="m", warmup=3)],
                       clock=clock, registry=reg, cooldown_s=120.0)
        for _ in range(4):
            clock.advance(10.0)
            mon.step({"m": 1000.0})
        fired = []
        # Flap: bad/good alternating at 10 s — only the FIRST bad
        # interval may open a record inside the 120 s cooldown.
        for i in range(6):
            clock.advance(10.0)
            value = 100.0 if i % 2 == 0 else 1000.0
            fired += mon.step({"m": value})
        assert len(fired) == 1
        snap = reg.snapshot()
        assert snap["health/anomalies_total"] == 1.0
        assert snap["health/suppressed_total"] >= 2.0
        # After the cooldown expires the detector may alarm again.
        clock.advance(200.0)
        assert len(mon.step({"m": 100.0})) == 1

    def test_primed_baseline_fires_inside_warmup(self, tmp_path):
        artifact = {"metric": "x", "value": 1, "unit": "fps",
                    "vs_baseline": 1.0,
                    "e2e_env_frames_per_sec": 50_000.0}
        (tmp_path / "BENCH_r07.json").write_text(json.dumps(artifact))
        clock = _FakeClock()
        mon = _monitor(default_detectors(warmup=8), clock=clock)
        assert mon.prime_from_bench(str(tmp_path)) == "BENCH_r07.json"
        clock.advance(10.0)
        # First-ever sample, deep inside warm-up: 20k is under half the
        # committed 50k baseline -> immediate primed trip.
        fired = mon.step({"learner/fps": 20_000.0}, update=1)
        assert [r["detector"] for r in fired] == ["throughput"]
        record = fired[0]
        assert record["primed"] is True
        assert record["baseline"] == 50_000.0
        assert record["baseline_source"] == "BENCH_r07.json"
        assert record["z"] is None

    def test_prime_from_committed_rounds(self):
        """The real repo root carries parseable BENCH rounds with the
        throughput keys — 'auto' priming must find them."""
        mon = _monitor(default_detectors())
        assert mon.prime_from_bench(REPO_ROOT) is not None

    def test_nonfinite_rate_detector(self):
        clock = _FakeClock()
        mon = _monitor([spec for spec in default_detectors()
                        if spec.name == "nonfinite"], clock=clock)
        clock.advance(10.0)
        assert mon.step(
            {"learner/nonfinite_skips_total": 0.0}) == []  # reference
        clock.advance(10.0)
        assert mon.step({"learner/nonfinite_skips_total": 0.0}) == []
        clock.advance(10.0)
        fired = mon.step({"learner/nonfinite_skips_total": 2.0})
        assert [r["detector"] for r in fired] == ["nonfinite"]
        assert fired[0]["observed"] == pytest.approx(0.2)  # 2 per 10 s
        # The nonfinite guard owns its own forensics: never pin.
        assert fired[0]["flightrec"]["pinned"] is False

    def test_peers_alive_learns_fleet_size_from_first_sample(self):
        clock = _FakeClock()
        mon = _monitor([spec for spec in default_detectors()
                        if spec.name == "peers_alive"], clock=clock)
        for _ in range(2):
            clock.advance(10.0)
            assert mon.step({"fleet/peers_alive": 2.0}) == []
        clock.advance(10.0)
        fired = mon.step({"fleet/peers_alive": 1.0})
        assert [r["detector"] for r in fired] == ["peers_alive"]
        assert fired[0]["baseline"] == 2.0


class TestRecordSchemaAndArtifact:
    def _trip(self, tmp_path, **monitor_kwargs):
        clock = _FakeClock()
        recorder = _StubRecorder()
        mon = _monitor([DetectorSpec(name="fps", metric="m", warmup=2)],
                       clock=clock, logdir=str(tmp_path),
                       recorder=recorder, **monitor_kwargs)
        for _ in range(3):
            clock.advance(10.0)
            mon.step({"m": 1000.0})
        clock.advance(10.0)
        (record,) = mon.step({"m": 100.0}, update=7,
                             verdict="env_bound",
                             evidence={"ledger_dominant": "unroll",
                                       "ledger_dominant_share": 0.8})
        return mon, record, recorder

    def test_record_schema_and_pin_protocol(self, tmp_path):
        mon, record, recorder = self._trip(tmp_path)
        assert record["schema_version"] == 1
        assert record["id"] == "a001-fps"
        assert record["kind"] == "ewma"
        assert record["metric"] == "m"
        assert record["update"] == 7
        assert record["verdict"] == "env_bound"
        assert record["dominant_segment"] == "unroll"
        assert record["dominant_share"] == 0.8
        assert record["flightrec"] == {"pinned": True,
                                       "dump": "health:a001-fps"}
        assert recorder.reason_pin == "health:a001-fps"
        assert ("anomaly", "fps", {"id": "a001-fps", "metric": "m"}) \
            in recorder.events
        # The event-sourced artifact round-trips.
        (reread,) = read_anomalies(str(tmp_path))
        assert reread["id"] == record["id"]
        assert reread["window"]["status"] == "armed"

    def test_existing_pin_is_never_demoted(self, tmp_path):
        clock = _FakeClock()
        recorder = _StubRecorder()
        recorder.reason_pin = "nonfinite:no_rollback"
        mon = _monitor([DetectorSpec(name="fps", metric="m", warmup=2)],
                       clock=clock, logdir=str(tmp_path),
                       recorder=recorder)
        for _ in range(3):
            clock.advance(10.0)
            mon.step({"m": 1000.0})
        clock.advance(10.0)
        (record,) = mon.step({"m": 100.0})
        assert recorder.reason_pin == "nonfinite:no_rollback"
        assert record["flightrec"]["pinned"] is False
        assert record["flightrec"]["dump"] == "nonfinite:no_rollback"

    def test_read_anomalies_skips_torn_tail(self, tmp_path):
        path = tmp_path / ANOMALIES_JSONL
        path.write_text(json.dumps({"id": "a001-x", "detector": "x"})
                        + "\n" + '{"id": "a002-y", "detec')
        records = read_anomalies(str(tmp_path))
        assert [r["id"] for r in records] == ["a001-x"]

    def test_last_record_per_id_wins(self, tmp_path):
        path = tmp_path / ANOMALIES_JSONL
        path.write_text(
            json.dumps({"id": "a001-x", "window": {"status": "armed"}})
            + "\n"
            + json.dumps({"id": "a001-x", "window": {"status": "done"}})
            + "\n")
        (record,) = read_anomalies(str(tmp_path))
        assert record["window"]["status"] == "done"


class TestWindowArbitration:
    def _specs(self):
        return [DetectorSpec(name="a", metric="ma", warmup=2),
                DetectorSpec(name="b", metric="mb", warmup=2)]

    def _warm(self, mon, clock, steps=3):
        for _ in range(steps):
            clock.advance(10.0)
            mon.step({"ma": 1000.0, "mb": 1000.0})

    def test_busy_budget_and_cooldown(self, tmp_path):
        clock = _FakeClock()
        mon = _monitor(self._specs(), clock=clock,
                       logdir=str(tmp_path), cooldown_s=120.0,
                       max_windows=2)
        self._warm(mon, clock)
        clock.advance(10.0)
        (rec_a,) = mon.step({"ma": 100.0, "mb": 1000.0})
        assert rec_a["window"]["status"] == "armed"
        assert mon.poll_window() == rec_a["id"]
        assert mon.poll_window() == rec_a["id"]  # poll does not consume
        mon.note_window_open(rec_a["id"], trace_dir="/t")
        # While a window is open, a second trip cannot arm another.
        clock.advance(10.0)
        (rec_b,) = mon.step({"ma": 100.0, "mb": 100.0})
        assert rec_b["window"]["status"] == "skipped:busy"
        mon.note_window_result(
            rec_a["id"],
            {"worst_kernel": "f.1", "worst_kernel_mfu": 0.3,
             "dominant_kernel": "f.1", "kernels": [
                 {"name": "f.1", "mfu": 0.3, "time_us": 180.0}]},
            kernels_json="k.json")
        # Window cooldown: 60 s after the open is inside the 120 s
        # window cooldown even though detector b's own cooldown has
        # NOT expired — advance past the detector cooldown but keep
        # the window one active via a fresh detector.
        clock.advance(170.0)  # t = open + 180 > 120: cooldown clear
        (rec_b2,) = mon.step({"ma": 1000.0, "mb": 100.0})
        assert rec_b2["window"]["status"] == "armed"
        mon.note_window_open(rec_b2["id"])
        mon.note_window_result(rec_b2["id"], None)
        # Budget exhausted (max_windows=2): further trips skip.
        clock.advance(170.0)
        (rec_a2,) = mon.step({"ma": 100.0, "mb": 1000.0})
        assert rec_a2["window"]["status"] == "skipped:budget"

    def test_window_cooldown_skips(self, tmp_path):
        clock = _FakeClock()
        mon = _monitor(self._specs(), clock=clock,
                       logdir=str(tmp_path), cooldown_s=120.0,
                       max_windows=5)
        self._warm(mon, clock)
        clock.advance(10.0)
        (rec_a,) = mon.step({"ma": 100.0, "mb": 1000.0})
        mon.note_window_open(rec_a["id"])
        mon.note_window_result(rec_a["id"], None)
        # Detector b trips for the FIRST time (no detector cooldown)
        # 60 s after the window opened: the window cooldown gates it.
        clock.advance(60.0)
        (rec_b,) = mon.step({"ma": 1000.0, "mb": 100.0})
        assert rec_b["window"]["status"] == "skipped:cooldown"

    def test_result_carries_worst_kernel_delta(self, tmp_path):
        clock = _FakeClock()
        mon = _monitor(self._specs(), clock=clock,
                       logdir=str(tmp_path), cooldown_s=0.0,
                       max_windows=1)
        mon.note_baseline_kernels(
            {"worst_kernel": "f.1", "worst_kernel_mfu": 0.5,
             "kernels": [{"name": "f.1", "mfu": 0.5,
                          "time_us": 100.0}]})
        self._warm(mon, clock)
        clock.advance(10.0)
        (record,) = mon.step({"ma": 100.0, "mb": 1000.0})
        mon.note_window_open(record["id"], trace_dir="/t")
        mon.note_window_result(
            record["id"],
            {"worst_kernel": "f.1", "worst_kernel_mfu": 0.3,
             "dominant_kernel": "f.1",
             "kernels": [{"name": "f.1", "mfu": 0.3,
                          "time_us": 180.0}]},
            kernels_json="kernels.a001-a.json")
        (final,) = read_anomalies(str(tmp_path))
        window = final["window"]
        assert window["status"] == "done"
        assert window["kernels_json"] == "kernels.a001-a.json"
        assert window["worst_kernel"] == "f.1"
        assert window["baseline_worst_kernel"] == "f.1"
        assert window["worst_kernel_mfu_delta"] == pytest.approx(-0.2)
        assert window["worst_kernel_time_delta_us"] == pytest.approx(80.0)

    def test_flush_finalizes_open_records(self, tmp_path):
        clock = _FakeClock()
        mon = _monitor(self._specs(), clock=clock,
                       logdir=str(tmp_path), cooldown_s=0.0,
                       max_windows=2)
        self._warm(mon, clock)
        clock.advance(10.0)
        (rec_a,) = mon.step({"ma": 100.0, "mb": 1000.0})
        mon.note_window_open(rec_a["id"])
        clock.advance(130.0)
        (rec_b,) = mon.step({"ma": 1000.0, "mb": 100.0})
        # b armed while a is... a is open, so b was skipped:busy —
        # release a's slot first so b can arm.
        assert rec_b["window"]["status"] == "skipped:busy"
        mon.flush()
        by_id = {r["id"]: r for r in read_anomalies(str(tmp_path))}
        assert by_id[rec_a["id"]]["window"]["status"] \
            == "aborted:run_ended"

    def test_flush_skips_never_opened_armed_window(self, tmp_path):
        clock = _FakeClock()
        mon = _monitor(self._specs(), clock=clock,
                       logdir=str(tmp_path), cooldown_s=0.0)
        self._warm(mon, clock)
        clock.advance(10.0)
        (record,) = mon.step({"ma": 100.0, "mb": 1000.0})
        assert record["window"]["status"] == "armed"
        mon.flush()
        (final,) = read_anomalies(str(tmp_path))
        assert final["window"]["status"] == "skipped:run_ended"
        assert mon.poll_window() is None


class TestFleetFold:
    def test_health_series_fold_rules(self):
        # One-hot fired gauges and the open-anomaly level: "did ANY
        # process see it" — max.
        assert aggregate._fleet_fold(
            "impala_health_fired_throughput",
            "impala_health_fired_throughput", "gauge", ()) == "max"
        assert aggregate._fleet_fold(
            "impala_health_open_anomalies",
            "impala_health_open_anomalies", "gauge", ()) == "max"
        # The totals are real counters: the kind rule sums them.
        assert aggregate._fleet_fold(
            "impala_health_anomalies_total",
            "impala_health_anomalies_total", "counter", ()) == "sum"


def _write_synthetic_logdir(logdir):
    os.makedirs(logdir, exist_ok=True)
    with open(os.path.join(logdir, "metrics.prom"), "w") as f:
        f.write(
            "# TYPE impala_learner_fps gauge\n"
            "impala_learner_fps 1000.0\n"
            "# TYPE impala_actor_fps gauge\n"
            "impala_actor_fps 1200.0\n"
            "# TYPE impala_ledger_mfu gauge\n"
            "impala_ledger_mfu 0.12\n"
            "# TYPE impala_fleet_peers_alive gauge\n"
            "impala_fleet_peers_alive 2.0\n"
            "# TYPE impala_health_suppressed_total counter\n"
            "impala_health_suppressed_total 1.0\n"
            "# TYPE impala_health_profile_windows_total counter\n"
            "impala_health_profile_windows_total 1.0\n"
            "# TYPE impala_ledger_latency_share_device gauge\n"
            "impala_ledger_latency_share_device 0.6\n"
            "# TYPE impala_ledger_latency_share_unroll gauge\n"
            "impala_ledger_latency_share_unroll 0.2\n"
            "# TYPE impala_ledger_staleness_s summary\n"
            'impala_ledger_staleness_s{quantile="0.95"} 0.5\n')
    with open(os.path.join(logdir, ANOMALIES_JSONL), "w") as f:
        f.write(json.dumps({
            "id": "a001-throughput", "detector": "throughput",
            "metric": "learner/fps", "observed": 250.0,
            "baseline": 1000.0, "z": 6.1,
            "window": {"status": "done",
                       "worst_kernel": "loss_grad_fusion",
                       "worst_kernel_mfu": 0.11,
                       "worst_kernel_mfu_delta": -0.2}}) + "\n")
        f.write(json.dumps({
            "id": "a002-staleness", "detector": "staleness",
            "metric": "ledger/staleness_s/p95", "observed": 4.0,
            "baseline": 0.5, "z": 5.0,
            "window": {"status": "armed"}}) + "\n")


class TestWatchConsole:
    def test_build_payload_on_synthetic_logdir(self, tmp_path):
        from scalable_agent_tpu.obs import watch

        logdir = str(tmp_path / "run")
        _write_synthetic_logdir(logdir)
        payload = watch.build_payload(logdir,
                                      bench_dir=str(tmp_path / "none"))
        assert payload["fps"]["learner"] == 1000.0
        assert payload["verdict"]["dominant_segment"] == "device"
        assert payload["staleness_p95_s"] == 0.5
        assert payload["health"]["anomalies"] == 2
        assert payload["health"]["open"] == 1
        assert payload["health"]["profile_windows"] == 1.0
        text = watch.render(payload)
        assert "a001-throughput" in text
        assert "loss_grad_fusion" in text
        assert "anomalies  2 total (1 open" in text

    def test_vs_baseline_uses_committed_rounds(self, tmp_path):
        from scalable_agent_tpu.obs import watch

        logdir = str(tmp_path / "run")
        _write_synthetic_logdir(logdir)
        payload = watch.build_payload(logdir, bench_dir=REPO_ROOT)
        assert payload["baseline"] is not None
        assert payload["fps"]["vs_baseline"] is not None

    def test_missing_logdir_exits_2_in_process(self, tmp_path, capsys):
        from scalable_agent_tpu.obs import watch

        assert watch.main([str(tmp_path / "nope"), "--once"]) == 2
        assert "obs.watch:" in capsys.readouterr().err

    def test_metrics_free_logdir_exits_2_in_process(self, tmp_path,
                                                    capsys):
        from scalable_agent_tpu.obs import watch

        empty = tmp_path / "empty"
        empty.mkdir()
        assert watch.main([str(empty), "--once"]) == 2
        err = capsys.readouterr().err
        assert "obs.watch:" in err and "metrics" in err

    def test_once_json_emits_payload(self, tmp_path, capsys):
        from scalable_agent_tpu.obs import watch

        logdir = str(tmp_path / "run")
        _write_synthetic_logdir(logdir)
        assert watch.main([logdir, "--once", "--json",
                           "--bench_dir", str(tmp_path / "none")]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["health"]["anomalies"] == 2


class TestCLIExitCodes:
    """Satellite 2: both jax-free CLIs exit 2 with a one-line
    diagnosis on a missing/metrics-free logdir — as subprocesses, the
    way an operator hits them."""

    def test_watch_subprocess_exit_2(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "scalable_agent_tpu.obs.watch",
             str(tmp_path / "missing"), "--once"],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
        assert proc.returncode == 2
        assert proc.stderr.strip().startswith("obs.watch:")
        assert len(proc.stderr.strip().splitlines()) == 1

    def test_report_subprocess_exit_2(self, tmp_path):
        empty = tmp_path / "empty"
        empty.mkdir()
        proc = subprocess.run(
            [sys.executable, "-m", "scalable_agent_tpu.obs.report",
             str(empty)],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
        assert proc.returncode == 2
        assert proc.stderr.strip().startswith("obs.report:")
        assert len(proc.stderr.strip().splitlines()) == 1

    def test_watch_subprocess_json_payload(self, tmp_path):
        logdir = str(tmp_path / "run")
        _write_synthetic_logdir(logdir)
        proc = subprocess.run(
            [sys.executable, "-m", "scalable_agent_tpu.obs.watch",
             logdir, "--once", "--json",
             "--bench_dir", str(tmp_path / "none")],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
        assert proc.returncode == 0, proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["logdir"] == logdir
        assert payload["health"]["anomalies"] == 2


class TestReportAndRoundsSections:
    def test_report_carries_anomalies_section(self, tmp_path, capsys):
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        _write_synthetic_logdir(logdir)
        payload = report.build_report(logdir)
        assert payload["anomalies"] is not None
        ids = [a["id"] for a in payload["anomalies"]]
        assert ids == ["a001-throughput", "a002-staleness"]
        assert report.main([logdir]) == 0
        out = capsys.readouterr().out
        assert "anomalies (2 recorded" in out
        assert "a001-throughput" in out

    def test_report_without_anomalies_is_none(self, tmp_path):
        from scalable_agent_tpu.obs import report

        logdir = str(tmp_path / "run")
        _write_synthetic_logdir(logdir)
        os.remove(os.path.join(logdir, ANOMALIES_JSONL))
        assert report.build_report(logdir)["anomalies"] is None

    def test_rounds_trajectory_carries_anomalies(self, tmp_path,
                                                 capsys):
        from scalable_agent_tpu.obs import rounds

        artifact = {"metric": "x", "value": 1, "unit": "fps",
                    "vs_baseline": 1.0,
                    "e2e_env_frames_per_sec": 9000.0,
                    "anomalies": [{
                        "id": "a001-throughput",
                        "detector": "throughput",
                        "metric": "learner/fps", "observed": 250.0,
                        "baseline": 1000.0, "z": 6.1,
                        "window": {"status": "done"}}]}
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(artifact))
        trajectory = rounds.build_trajectory(str(tmp_path))
        assert 1 in trajectory["anomalies"]
        assert trajectory["anomalies"][1][0]["id"] == "a001-throughput"
        text = rounds.render_trajectory(trajectory)
        assert "run-health anomalies (obs/health.py):" in text
        assert "a001-throughput" in text
        assert rounds.main(["report", "--json",
                            "--bench_dir", str(tmp_path)]) == 0
        machine = json.loads(capsys.readouterr().out)
        assert machine["anomalies"]["1"][0]["id"] == "a001-throughput"


class TestHTTPRoutes:
    def test_anomalies_and_health_routes(self, tmp_path):
        logdir = str(tmp_path / "run")
        _write_synthetic_logdir(logdir)
        registry = MetricsRegistry()
        registry.counter("scrapes").inc()
        with MetricsHTTPServer(registry, port=0,
                               logdir=logdir) as server:
            base = f"http://127.0.0.1:{server.port}"
            body = urllib.request.urlopen(
                f"{base}/anomalies", timeout=5).read().decode()
            lines = [json.loads(line)
                     for line in body.splitlines() if line.strip()]
            assert [r["id"] for r in lines] \
                == ["a001-throughput", "a002-staleness"]
            health = json.loads(urllib.request.urlopen(
                f"{base}/health", timeout=5).read().decode())
            assert health["health"]["anomalies"] == 2
            # The plain scrape still works next to the new routes.
            metrics = urllib.request.urlopen(
                f"{base}/metrics", timeout=5).read().decode()
            assert "impala_scrapes" in metrics

    def test_health_route_503_before_first_snapshot(self, tmp_path):
        logdir = str(tmp_path / "empty")
        os.makedirs(logdir)
        with MetricsHTTPServer(MetricsRegistry(), port=0,
                               logdir=logdir) as server:
            base = f"http://127.0.0.1:{server.port}"
            # No anomalies yet: an empty, valid NDJSON stream.
            assert urllib.request.urlopen(
                f"{base}/anomalies", timeout=5).read() == b""
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/health", timeout=5)
            assert err.value.code == 503

    def test_routes_absent_without_logdir(self):
        with MetricsHTTPServer(MetricsRegistry(), port=0) as server:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/anomalies",
                    timeout=5)
            assert err.value.code == 404


# -- the tier-1 acceptance run ----------------------------------------------


def _health_config(tmp_path, **overrides):
    from scalable_agent_tpu.config import Config

    base = dict(
        mode="train",
        logdir=str(tmp_path / "run"),
        level_name="fake_small",
        num_actors=4,
        batch_size=2,
        unroll_length=4,
        num_action_repeats=1,
        total_environment_frames=96,  # 12 updates of 8 frames
        height=16,
        width=16,
        num_env_workers_per_group=2,
        compute_dtype="float32",
        checkpoint_interval_s=1e9,
        log_interval_s=0.0,
        seed=5,
        # 12 update-cadence intervals: the first 6 (compile-dominated,
        # noisy-loss warm-in) only build baselines; the z floor rides
        # above the batch-2 run's genuine loss swings (4 <-> 21), while
        # the sag's ~97% relative fps drop trips the rel path on its
        # own.
        health_warmup_intervals=6,
        health_z_threshold=6.0,
        health_max_windows=1,
        health_window_updates=2,
    )
    base.update(overrides)
    return Config(**base)


@pytest.fixture(autouse=True)
def _clean_faults():
    from scalable_agent_tpu.runtime import configure_faults

    configure_faults("")
    yield
    configure_faults("")


@pytest.mark.chaos
def test_throughput_sag_drives_the_full_anomaly_protocol(
        tmp_path, monkeypatch, capsys):
    """The acceptance loop: a chaos-injected mid-run slowdown must (1)
    land a throughput anomaly record with attribution, (2) pin + dump
    the flight recorder, and (3) auto-profile exactly one window whose
    harvested kernel ledger is referenced from the final record."""
    from scalable_agent_tpu.driver import train as run_train
    from scalable_agent_tpu.obs import get_registry, report

    monkeypatch.setenv("SCALABLE_AGENT_LEDGER_MFU_PEAK", "1e12")
    config = _health_config(tmp_path,
                            chaos_spec="throughput_sag@8:11")
    # The registry is a process singleton: health counters accumulate
    # across every driver test in the session, so assert deltas.
    before = get_registry().snapshot()
    windows_before = before.get("health/profile_windows_total", 0.0)
    metrics = run_train(config)
    assert metrics["env_frames"] == 96

    records = read_anomalies(config.logdir)
    throughput = [r for r in records if r["detector"] == "throughput"]
    assert throughput, records
    record = throughput[0]
    assert record["observed"] < record["baseline"]
    assert record["rel"] >= 0.6
    # Attribution at trip time: the host backend runs the stall
    # attributor and the ledger, so the record names at least one.
    assert (record["verdict"] is not None
            or record["dominant_segment"] is not None), record

    # (2) pinned + dumped flight recorder.
    assert record["flightrec"]["pinned"] is True
    assert record["flightrec"]["dump"] == f"health:{record['id']}"
    assert glob.glob(os.path.join(config.logdir, "flightrec.*.json"))

    # (3) exactly one auto-profile window, done, with the harvested
    # per-anomaly kernel ledger written back into the record.
    assert record["window"]["status"] == "done", record
    kernels_json = record["window"]["kernels_json"]
    assert os.path.basename(kernels_json) \
        == f"kernels.{record['id']}.json"
    assert os.path.exists(kernels_json)
    table = json.load(open(kernels_json))
    assert table["kernels"] and table["dominant_kernel"]
    assert record["window"]["worst_kernel"]

    prom = open(os.path.join(config.logdir, "metrics.prom")).read()
    assert "impala_health_profile_windows_total" in prom
    assert "impala_health_anomalies_total" in prom
    after = get_registry().snapshot()
    assert after.get("health/profile_windows_total", 0.0) \
        - windows_before == 1.0
    assert len(glob.glob(os.path.join(
        config.logdir, "health_profile.*"))) == 1

    # The second sag (occurrence 8) fell inside the cooldown: one
    # throughput record total, suppressions counted.
    assert len(throughput) == 1

    # The consoles surface it: watch --once --json and the report.
    from scalable_agent_tpu.obs import watch

    assert watch.main([config.logdir, "--once", "--json",
                       "--bench_dir",
                       str(tmp_path / "nobench")]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["health"]["anomalies"] >= 1
    assert any(r["detector"] == "throughput"
               for r in payload["health"]["recent"])

    assert report.main(["--json", config.logdir]) == 0
    machine = json.loads(capsys.readouterr().out)
    assert machine["anomalies"] is not None
    assert any(a["id"] == record["id"] for a in machine["anomalies"])


@pytest.mark.chaos
def test_clean_run_stays_anomaly_free(tmp_path):
    """The same config without chaos: zero anomalies — the detectors'
    warm-up + thresholds must absorb normal CPU-run jitter."""
    from scalable_agent_tpu.driver import train as run_train
    from scalable_agent_tpu.obs import get_registry

    config = _health_config(tmp_path)
    before = get_registry().snapshot().get("health/anomalies_total", 0.0)
    metrics = run_train(config)
    assert metrics["env_frames"] == 96
    assert read_anomalies(config.logdir) == []
    prom = open(os.path.join(config.logdir, "metrics.prom")).read()
    assert "impala_health_anomalies_total" in prom
    after = get_registry().snapshot().get("health/anomalies_total", 0.0)
    assert after - before == 0.0
