"""Unit tests for the observability subsystem (obs/)."""

import json
import os
import threading
import time

import numpy as np
import pytest

from scalable_agent_tpu import obs
from scalable_agent_tpu.obs import (
    MetricsRegistry,
    MetricsWriter,
    StallAttributor,
    Tracer,
    load_trace_events,
    render_prometheus,
)
from scalable_agent_tpu.runtime.batcher import DynamicBatcher
from scalable_agent_tpu.utils import Timing


class TestTracer:
    def test_span_nesting_and_ordering(self, tmp_path):
        path = str(tmp_path / "trace.json")
        with Tracer(path, annotate=False) as tracer:
            with tracer.span("outer", cat="test"):
                time.sleep(0.001)
                with tracer.span("inner", cat="test"):
                    time.sleep(0.001)
                time.sleep(0.001)
        events = list(load_trace_events(path))
        complete = {e["name"]: e for e in events if e["ph"] == "X"}
        assert set(complete) == {"outer", "inner"}
        outer, inner = complete["outer"], complete["inner"]
        # Same process/thread track; nesting expressed by containment.
        assert outer["pid"] == inner["pid"] == os.getpid()
        assert outer["tid"] == inner["tid"]
        assert inner["ts"] >= outer["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
        assert inner["dur"] < outer["dur"]
        # The inner span exits (and is therefore emitted) first.
        names = [e["name"] for e in events if e["ph"] == "X"]
        assert names == ["inner", "outer"]

    def test_metadata_and_instant_and_counter_events(self, tmp_path):
        path = str(tmp_path / "trace.json")
        with Tracer(path, annotate=False,
                    process_name="test_proc") as tracer:
            with tracer.span("s"):
                pass
            tracer.instant("mark", args={"k": 1})
            tracer.counter("queues", {"depth": 3})
        events = list(load_trace_events(path))
        metas = [e for e in events if e["ph"] == "M"]
        assert any(e["name"] == "process_name"
                   and e["args"]["name"] == "test_proc" for e in metas)
        assert any(e["name"] == "thread_name" for e in metas)
        assert any(e["ph"] == "i" and e["name"] == "mark" for e in events)
        counter = next(e for e in events if e["ph"] == "C")
        assert counter["args"] == {"depth": 3.0}

    def test_file_is_perfetto_loadable_json_array(self, tmp_path):
        """The unclosed-array trace becomes strict JSON by appending a
        terminator — the format Perfetto/chrome://tracing parse."""
        path = str(tmp_path / "trace.json")
        with Tracer(path, annotate=False) as tracer:
            with tracer.span("a"):
                pass
        text = open(path).read()
        assert text.startswith("[\n")
        events = json.loads(text.rstrip().rstrip(",") + "]")
        assert isinstance(events, list) and events

    def test_disabled_tracer_is_noop_and_allocation_free(self, tmp_path):
        tracer = Tracer(path=None)
        span_a = tracer.span("x")
        span_b = tracer.span("y")
        assert span_a is span_b  # the shared null singleton
        with span_a:
            pass
        tracer.instant("m")
        tracer.counter("c", {"v": 1})
        tracer.close()

    def test_concurrent_spans_keep_per_thread_tracks(self, tmp_path):
        path = str(tmp_path / "trace.json")
        tracer = Tracer(path, annotate=False)
        # All 4 threads must be alive simultaneously: the OS recycles
        # thread idents, so a sequential finish could alias tids.
        barrier = threading.Barrier(4)

        def worker():
            barrier.wait(timeout=10)
            for _ in range(20):
                with tracer.span("w"):
                    pass

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tracer.close()
        events = [e for e in load_trace_events(path) if e["ph"] == "X"]
        assert len(events) == 80
        assert len({e["tid"] for e in events}) == 4

    def test_event_budget_truncates_with_marker(self, tmp_path):
        """The max_events budget stops capture (disk/Perfetto bound) but
        leaves a loadable file whose tail names the truncation."""
        path = str(tmp_path / "trace.json")
        tracer = obs.configure_tracer(path, annotate=False, max_events=5)
        for _ in range(20):
            with tracer.span("s"):
                pass
        assert not tracer.enabled  # budget exhausted -> capture off
        # The teardown path must still flush the tail even though the
        # budget already flipped enabled off (regression: the swap used
        # to gate close() on `enabled` and leaked the buffered marker).
        obs.configure_tracer(None)
        assert tracer._file is None  # really closed
        events = list(load_trace_events(path))
        assert sum(1 for e in events if e["ph"] == "X") <= 5
        assert events[-1]["name"] == "trace_truncated"

    def test_load_tolerates_torn_tail_of_a_crashed_run(self, tmp_path):
        """A crash mid-write leaves a partial last line (possibly no
        newline); parsing must yield every complete event and drop the
        torn one silently."""
        path = str(tmp_path / "trace.json")
        with Tracer(path, annotate=False) as tracer:
            for _ in range(3):
                with tracer.span("s"):
                    pass
        text = open(path).read()
        cut = text.rstrip()
        cut = cut[:len(cut) - 17]  # sever the final event mid-JSON
        with open(path, "w") as f:
            f.write(cut)
        events = list(load_trace_events(path))
        assert events  # the intact head parsed
        assert all(isinstance(e, dict) for e in events)
        complete = [e for e in events if e.get("ph") == "X"]
        assert len(complete) == 2  # the torn third span was dropped

    def test_epoch_record_written_and_parseable(self, tmp_path):
        path = str(tmp_path / "trace.json")
        with Tracer(path, annotate=False, process_index=5):
            pass
        (epoch,) = [e for e in load_trace_events(path)
                    if e["name"] == "trace_epoch"]
        args = epoch["args"]
        assert args["process_index"] == 5
        # The pair is back-to-back readings of wall and span clocks.
        assert args["unix_time_us"] > 1e15
        assert args["perf_time_us"] == epoch["ts"]
        meta = [e for e in load_trace_events(path)
                if e["name"] == "process_sort_index"]
        assert meta and meta[0]["args"]["sort_index"] == 5

    def test_load_parses_strict_closed_arrays_too(self, tmp_path):
        """The aggregator writes STRICT closed arrays; the same loader
        must read both formats."""
        path = str(tmp_path / "merged.json")
        with open(path, "w") as f:
            f.write('[\n{"name": "a", "ph": "X", "ts": 1, "dur": 2, '
                    '"pid": 1, "tid": 1},\n'
                    '{"name": "b", "ph": "X", "ts": 3, "dur": 4, '
                    '"pid": 1, "tid": 1}\n]\n')
        assert [e["name"] for e in load_trace_events(path)
                if e.get("ph") == "X"] == ["a", "b"]

    def test_global_configure_roundtrip(self, tmp_path):
        path = str(tmp_path / "trace.json")
        tracer = obs.configure_tracer(path, annotate=False)
        assert obs.get_tracer() is tracer
        with obs.span("global_span"):
            pass
        obs.configure_tracer(None)  # closes + flushes the file tracer
        assert not obs.get_tracer().enabled
        names = [e["name"] for e in load_trace_events(path)
                 if e["ph"] == "X"]
        assert names == ["global_span"]


class TestHistogram:
    def test_percentiles_match_numpy(self):
        rng = np.random.RandomState(7)
        samples = rng.lognormal(size=500)
        registry = MetricsRegistry()
        hist = registry.histogram("lat", window=1000)
        for s in samples:
            hist.observe(float(s))
        quantiles = hist.quantiles()
        for q in (0.5, 0.95, 0.99):
            np.testing.assert_allclose(
                quantiles[q], np.percentile(samples, q * 100), rtol=1e-12)
        assert hist.count == 500
        np.testing.assert_allclose(hist.sum, samples.sum(), rtol=1e-9)

    def test_window_bounds_quantiles_but_not_count(self):
        registry = MetricsRegistry()
        hist = registry.histogram("lat", window=10)
        for v in range(100):
            hist.observe(float(v))
        assert hist.count == 100  # exact lifetime count
        # Quantiles only see the last 10 observations (90..99).
        assert hist.quantiles()[0.5] == pytest.approx(
            np.percentile(np.arange(90, 100), 50))

    def test_timer_context(self):
        registry = MetricsRegistry()
        hist = registry.histogram("t")
        with hist.time():
            time.sleep(0.005)
        assert hist.count == 1
        assert hist.sum >= 0.004


class TestRegistry:
    def test_idempotent_registration(self):
        registry = MetricsRegistry()
        assert registry.counter("a") is registry.counter("a")
        with pytest.raises(ValueError, match="already registered"):
            registry.gauge("a")

    def test_counter_monotonic(self):
        counter = MetricsRegistry().counter("c")
        counter.inc()
        counter.inc(2.5)
        assert counter.value == 3.5
        with pytest.raises(ValueError):
            counter.inc(-1)

    def test_callback_gauge_sampled_at_snapshot(self):
        registry = MetricsRegistry()
        box = {"v": 1.0}
        registry.gauge("g", fn=lambda: box["v"])
        assert registry.snapshot()["g"] == 1.0
        box["v"] = 9.0
        assert registry.snapshot()["g"] == 9.0

    def test_failing_gauge_callback_reads_nan(self):
        registry = MetricsRegistry()

        def boom():
            raise RuntimeError("queue died")

        registry.gauge("g", fn=boom)
        assert np.isnan(registry.snapshot()["g"])

    def test_jax_compile_hooks_count_recompilations(self):
        import jax

        registry = MetricsRegistry().install_jax_hooks()
        before = registry.counter("jax/compile_count").value
        jax.jit(lambda x: x * 3.14159 + 2.71828)(np.float32(1.0))
        after = registry.counter("jax/compile_count").value
        assert after > before
        assert registry.counter("jax/compile_time_s").value > 0.0


class TestQueueDepthGauge:
    def test_depth_under_partial_fill_and_drain(self):
        registry = MetricsRegistry()
        batcher = DynamicBatcher(
            lambda tree, n: tree, minimum_batch_size=4,
            timeout_ms=None, metrics_name="qtest", registry=registry)
        try:
            futures = [batcher.compute_async(np.zeros(2, np.float32))
                       for _ in range(3)]
            # Below minimum_batch_size: requests sit in the queue.
            assert registry.snapshot()["qtest/queue_depth"] == 3.0
            futures.append(batcher.compute_async(np.zeros(2, np.float32)))
            for f in futures:
                f.result(timeout=5)
            deadline = time.monotonic() + 5
            while (registry.snapshot()["qtest/queue_depth"] > 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert registry.snapshot()["qtest/queue_depth"] == 0.0
            assert registry.snapshot()["qtest/batch_size/sum"] == 4.0
        finally:
            batcher.close()

    def test_depth_under_concurrent_produce_consume(self):
        registry = MetricsRegistry()
        batcher = DynamicBatcher(
            lambda tree, n: tree, minimum_batch_size=1,
            maximum_batch_size=8, timeout_ms=1.0,
            metrics_name="qtest2", registry=registry)
        n_threads, per_thread = 8, 25
        try:
            def producer():
                for _ in range(per_thread):
                    batcher.compute(np.zeros(2, np.float32))

            threads = [threading.Thread(target=producer)
                       for _ in range(n_threads)]
            for t in threads:
                t.start()
            depths = []
            while any(t.is_alive() for t in threads):
                depths.append(registry.snapshot()["qtest2/queue_depth"])
                time.sleep(0.001)
            for t in threads:
                t.join()
        finally:
            batcher.close()
        snapshot = registry.snapshot()
        # Everything submitted was batched and answered; the gauge reads
        # empty at quiescence and never went negative mid-flight.
        assert snapshot["qtest2/queue_depth"] == 0.0
        assert snapshot["qtest2/batch_size/sum"] == n_threads * per_thread
        assert snapshot["qtest2/request_latency_s/count"] == (
            n_threads * per_thread)
        assert all(d >= 0 for d in depths)
        assert snapshot["qtest2/occupancy/p99"] <= 1.0


class TestPrometheusRendering:
    def test_golden_exposition_text(self):
        registry = MetricsRegistry()
        counter = registry.counter("frames_total", "frames seen")
        counter.inc(1234)
        registry.gauge("queue/depth", "queued items").set(3)
        hist = registry.histogram("stage/latency_s", "stage seconds")
        for v in (1.0, 2.0, 3.0, 4.0):
            hist.observe(v)
        golden = (
            "# HELP impala_frames_total frames seen\n"
            "# TYPE impala_frames_total counter\n"
            "impala_frames_total 1234.0\n"
            "# HELP impala_queue_depth queued items\n"
            "# TYPE impala_queue_depth gauge\n"
            "impala_queue_depth 3.0\n"
            "# HELP impala_stage_latency_s stage seconds\n"
            "# TYPE impala_stage_latency_s summary\n"
            'impala_stage_latency_s{quantile="0.5"} 2.5\n'
            'impala_stage_latency_s{quantile="0.95"} 3.8499999999999996\n'
            'impala_stage_latency_s{quantile="0.99"} 3.9699999999999998\n'
            "impala_stage_latency_s_sum 10.0\n"
            "impala_stage_latency_s_count 4\n"
        )
        assert render_prometheus(registry) == golden

    def test_nan_and_digit_names_render_validly(self):
        registry = MetricsRegistry()
        registry.gauge("3d/weird-name")  # leading digit + dash
        text = render_prometheus(registry)
        assert "impala__3d_weird_name 0.0" in text

    def test_exporter_dumps_atomically(self, tmp_path):
        registry = MetricsRegistry()
        registry.counter("c").inc()
        exporter = obs.PrometheusExporter(
            registry, str(tmp_path / "metrics.prom"))
        text = exporter.dump()
        assert open(exporter.path).read() == text
        assert not os.path.exists(exporter.path + ".tmp")

    def test_render_under_concurrent_registry_mutation(self):
        """Rendering must stay exception-free and well-formed while
        other threads register instruments and feed observations —
        the HTTP endpoint renders on scraper threads mid-training."""
        registry = MetricsRegistry()
        stop = threading.Event()
        errors = []

        def mutator(slot):
            i = 0
            try:
                while not stop.is_set():
                    registry.counter(f"m{slot}/c{i % 50}").inc()
                    registry.histogram(
                        f"m{slot}/h{i % 50}").observe(i * 1e-3)
                    registry.gauge(f"m{slot}/g{i % 50}").set(i)
                    i += 1
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=mutator, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        try:
            # 40 renders over the growing registry exercise the race;
            # more just burns tier-1 wall clock (200 renders under 4
            # spinning mutators cost 3+ minutes on a 1-core CI host).
            for _ in range(40):
                text = render_prometheus(registry)
                for line in text.splitlines():
                    assert line.startswith("#") or len(
                        line.split()) == 2, line
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors


class TestStallAttributor:
    def _observe_actor(self, registry, env_s, infer_s):
        registry.histogram("actor/env_step_s").observe(env_s)
        registry.histogram("actor/inference_s").observe(infer_s)

    def test_device_bound_when_learner_saturated(self):
        registry = MetricsRegistry()
        attributor = StallAttributor(registry)
        category, evidence = attributor.attribute(
            wait_batch_s=0.01, update_s=1.0)
        assert category == "device_bound"
        assert registry.snapshot()["stall/is_device_bound"] == 1.0
        assert evidence["wait_frac"] < 0.15

    def test_env_bound_when_starved_and_env_dominates(self):
        registry = MetricsRegistry()
        attributor = StallAttributor(registry)
        self._observe_actor(registry, env_s=2.0, infer_s=0.2)
        category, _ = attributor.attribute(
            wait_batch_s=0.8, update_s=0.2)
        assert category == "env_bound"
        assert registry.snapshot()[
            "stall/intervals_env_bound_total"] == 1.0

    def test_learner_starved_when_inference_dominates(self):
        registry = MetricsRegistry()
        attributor = StallAttributor(registry)
        self._observe_actor(registry, env_s=0.1, infer_s=3.0)
        category, _ = attributor.attribute(
            wait_batch_s=0.8, update_s=0.2)
        assert category == "learner_starved"

    def test_interval_deltas_not_cumulative_sums(self):
        """The attributor differences the actor histogram sums, so an
        env-heavy PAST doesn't taint a now-inference-bound interval."""
        registry = MetricsRegistry()
        attributor = StallAttributor(registry)
        self._observe_actor(registry, env_s=100.0, infer_s=0.1)
        category, _ = attributor.attribute(0.9, 0.1)
        assert category == "env_bound"
        # New interval: only inference time accrues.
        self._observe_actor(registry, env_s=0.0, infer_s=5.0)
        category, _ = attributor.attribute(0.9, 0.1)
        assert category == "learner_starved"

    def test_prior_run_sums_do_not_taint_first_interval(self):
        """Construction baselines against the registry's CURRENT sums:
        a second train() on the process-global registry must not charge
        its first interval with the whole previous run's actor time."""
        registry = MetricsRegistry()
        self._observe_actor(registry, env_s=1000.0, infer_s=0.1)  # "run 1"
        attributor = StallAttributor(registry)
        category, evidence = attributor.attribute(0.9, 0.1)
        assert evidence["actor_env_s"] == 0.0
        assert category == "learner_starved"  # not env_bound from run 1

    def test_describe_is_log_ready(self):
        registry = MetricsRegistry()
        attributor = StallAttributor(registry)
        category, evidence = attributor.attribute(0.0, 1.0)
        line = StallAttributor.describe(category, evidence)
        assert "device_bound" in line and "%" in line

    def test_zero_length_interval_is_finite_and_device_bound(self):
        """A zero-second interval (two log ticks back-to-back) must not
        divide by zero; with no evidence the verdict defaults to the
        healthy category with all-zero fractions."""
        registry = MetricsRegistry()
        attributor = StallAttributor(registry)
        category, evidence = attributor.attribute(0.0, 0.0)
        assert category == "device_bound"
        assert evidence["wait_frac"] == 0.0
        assert evidence["actor_env_frac"] == 0.0
        snap = registry.snapshot()
        assert snap["stall/frac_wait_batch"] == 0.0
        assert snap["stall/frac_update"] == 0.0
        assert all(np.isfinite(v) for v in evidence.values())

    def test_missing_baseline_histograms_read_zero(self):
        """Constructing against a registry where the actor histograms
        were never fed (e.g. ingraph backend: no actor threads) must
        work — sums start at 0 and stay there."""
        registry = MetricsRegistry()
        attributor = StallAttributor(registry)
        category, evidence = attributor.attribute(0.9, 0.1)
        assert category == "learner_starved"  # starved, no env evidence
        assert evidence["actor_env_s"] == 0.0
        assert evidence["actor_infer_s"] == 0.0

    def test_all_zero_timings_after_active_interval(self):
        """An interval in which literally nothing ran (suspended run)
        must not reuse the previous interval's fractions."""
        registry = MetricsRegistry()
        attributor = StallAttributor(registry)
        self._observe_actor(registry, env_s=2.0, infer_s=0.5)
        attributor.attribute(0.8, 0.2)  # active interval
        category, evidence = attributor.attribute(0.0, 0.0)
        assert category == "device_bound"
        assert evidence["actor_env_s"] == 0.0

    def test_report_stalled_one_hots_the_watchdog_verdict(self):
        registry = MetricsRegistry()
        attributor = StallAttributor(registry)
        attributor.attribute(0.9, 0.1)  # a live verdict to displace
        line = attributor.report_stalled(
            {"actor-0": 12.34, "prefetch": 45.6})
        assert "stalled_thread" in line
        # Worst (longest-silent) thread leads the report.
        assert line.index("prefetch") < line.index("actor-0")
        snap = registry.snapshot()
        assert snap["stall/is_stalled_thread"] == 1.0
        assert snap["stall/is_learner_starved"] == 0.0
        assert snap["stall/intervals_stalled_thread_total"] == 1.0


class TestTimingSummary:
    def test_summary_unwraps_avg_and_plain_entries(self):
        timing = Timing()
        with timing.time_avg("a"):
            time.sleep(0.002)
        with timing.time_avg("a"):
            time.sleep(0.002)
        with timing.add_time("b"):
            time.sleep(0.001)
        with timing.timeit("c"):
            pass
        summary = timing.summary()
        assert set(summary) == {"a", "b", "c"}
        assert all(isinstance(v, float) for v in summary.values())
        assert summary["a"] == pytest.approx(timing["a"].value)
        assert summary["b"] >= 0.001


class TestMetricsWriter:
    def test_explicit_zero_wall_time_preserved(self, tmp_path):
        with MetricsWriter(str(tmp_path)) as writer:
            writer.write(0, {"x": 1.0}, wall_time=0.0)
            writer.write(1, {"x": 2.0})
        rows = [json.loads(line) for line in
                open(tmp_path / "metrics.jsonl")]
        assert rows[0]["time"] == 0.0  # `or time.time()` would clobber it
        assert rows[1]["time"] > 0.0

    def test_context_manager_closes_on_exception(self, tmp_path):
        with pytest.raises(RuntimeError):
            with MetricsWriter(str(tmp_path)) as writer:
                writer.write(0, {"x": 1.0})
                raise RuntimeError("loop died")
        assert writer._jsonl.closed
        rows = [json.loads(line) for line in
                open(tmp_path / "metrics.jsonl")]
        assert rows and rows[0]["x"] == 1.0  # flushed despite the raise

    def test_write_registry_namespaces_snapshot(self, tmp_path):
        registry = MetricsRegistry()
        registry.counter("frames_total").inc(5)
        with MetricsWriter(str(tmp_path), registry=registry) as writer:
            writer.write_registry(3)
        rows = [json.loads(line) for line in
                open(tmp_path / "metrics.jsonl")]
        assert rows[0]["obs/frames_total"] == 5.0
        assert rows[0]["step"] == 3
