"""Continuous-batching actor service (runtime/service.py, ISSUE 10).

Covers the three satellite contracts plus the tier-1 driver smoke:

- shared batch formation (bucket_ladder / pad_to_bucket — the code
  lifted out of both dynamic batchers);
- per-env trajectory packing: T+1 overlap layout BIT-IDENTICAL to
  ``VectorActor`` (the packer replays a VectorActor run's per-step rows
  in scrambled arrival order and must reproduce its trajectories
  exactly), stragglers buffer without stalling siblings, and a reset
  forces a fresh bootstrap;
- MultiEnv's per-worker async step API (slice outputs match the
  lockstep path; dead workers respawn per worker);
- the live service: learner-consumable [T+1, B] batches, worker_kill
  respawn mid-unroll, and the ``service_stall`` chaos point tripping
  the watchdog heartbeat;
- driver smoke: ``--actor=service`` end-to-end on the fake env with a
  complete, conservation-checked ledger artifact carrying the new
  ``service_*`` stages.
"""

import functools
import glob
import json
import os
import time

import jax
import numpy as np
import pytest

from scalable_agent_tpu.envs import MultiEnv, make_impala_stream
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.models import ImpalaAgent
from scalable_agent_tpu.models import agent as agent_mod
from scalable_agent_tpu.runtime import VectorActor
from scalable_agent_tpu.runtime.batcher import (
    DynamicBatcher,
    bucket_ladder,
    pad_to_bucket,
)
from scalable_agent_tpu.runtime.service import (
    ActorService,
    TrajectoryPacker,
)
from scalable_agent_tpu.types import AgentState, map_structure

NUM_ACTIONS = 5
FRAME = TensorSpec((16, 16, 3), np.uint8, "frame")
T = 5
B = 4


def make_envs(n=B, workers=2, seed_base=0):
    fns = [functools.partial(make_impala_stream, "fake_small",
                             seed=seed_base + i,
                             num_actions=NUM_ACTIONS)
           for i in range(n)]
    return MultiEnv(fns, FRAME, num_workers=workers)


@pytest.fixture(scope="module")
def agent_and_params():
    agent = ImpalaAgent(num_actions=NUM_ACTIONS)
    envs = make_envs(1, workers=1)
    try:
        params = agent.init(
            jax.random.key(0),
            np.zeros((1, 1), np.int32),
            jax.tree_util.tree_map(
                lambda x: None if x is None else np.asarray(x)[None][:, :1],
                envs.initial(), is_leaf=lambda x: x is None),
            agent_mod.initial_state(1))
    finally:
        envs.close()
    return agent, params


def tree_as_numpy(tree):
    return jax.tree_util.tree_map(
        lambda x: None if x is None else np.asarray(x), tree,
        is_leaf=lambda x: x is None)


def assert_trees_equal(a, b, msg=""):
    def check(x, y):
        if x is None or y is None:
            assert x is None and y is None, msg
            return None
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=msg)
        return None

    map_structure(check, a, b)


# ---------------------------------------------------------------------------
# Shared batch formation (lifted out of batcher.py / native_batcher.py)
# ---------------------------------------------------------------------------


class TestBatchFormation:
    def test_bucket_ladder_powers_of_two(self):
        assert bucket_ladder(8) == [1, 2, 4, 8]
        assert bucket_ladder(6) == [1, 2, 4, 6]
        assert bucket_ladder(1) == [1]
        assert bucket_ladder(8, minimum=4) == [4, 8]

    def test_bucket_ladder_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            bucket_ladder(0)

    def test_pad_to_bucket(self):
        sizes = bucket_ladder(8)
        assert pad_to_bucket(1, sizes) == 1
        assert pad_to_bucket(3, sizes) == 4
        assert pad_to_bucket(8, sizes) == 8
        assert pad_to_bucket(9, sizes) == 9  # beyond the ladder
        assert pad_to_bucket(3, None) == 3  # bucketing disabled

    def test_dynamic_batcher_uses_shared_policy(self):
        """The batcher's padding must BE the shared implementation —
        a formed batch of 3 against a [1,2,4,8] ladder pads to 4."""
        seen = []

        def compute(tree, n):
            seen.append((np.asarray(tree).shape[0], n))
            return np.asarray(tree)

        with DynamicBatcher(compute, maximum_batch_size=8,
                            timeout_ms=1.0,
                            pad_to_sizes=bucket_ladder(8)) as batcher:
            futures = [batcher.compute_async(np.float32(i))
                       for i in range(3)]
            for future in futures:
                future.result(timeout=10)
        padded_sizes = {shape for shape, _ in seen}
        valid = {n for _, n in seen}
        assert padded_sizes <= {1, 2, 4, 8}
        assert sum(valid) == 3


# ---------------------------------------------------------------------------
# Per-env trajectory packing
# ---------------------------------------------------------------------------


def _row(tree, t, e):
    """Entry (t, env e) of a [T+1, B, ...] tree as a width-1 lane row."""
    return map_structure(
        lambda x: None if x is None else np.asarray(x)[t, e:e + 1], tree)


def _replay_order(num_steps, num_envs, rng):
    """Per-step scrambled env visitation: every env appears once per
    step, order varies — the arrival interleaving continuous batching
    produces."""
    orders = []
    for _ in range(num_steps):
        order = list(range(num_envs))
        rng.shuffle(order)
        orders.append(order)
    return orders


class TestTrajectoryPacker:
    def test_bit_identical_to_vector_actor(self, agent_and_params):
        """Feed a packer the per-step rows of a real VectorActor run
        (same seeds), one env at a time in SCRAMBLED arrival order, and
        require bit-identical [T+1, B] trajectories — overlap entry,
        boundary agent_state, every leaf."""
        agent, params = agent_and_params
        envs = make_envs()
        try:
            actor = VectorActor(agent, envs, T, seed=7)
            reference = [tree_as_numpy(actor.run_unroll(params))
                         for _ in range(3)]
        finally:
            envs.close()

        packer = TrajectoryPacker([1] * B, T)
        first = reference[0]
        for e in range(B):
            packer.bootstrap(
                e, _row(first.env_outputs, 0, e),
                _row(first.agent_outputs, 0, e),
                np.asarray(first.agent_state.c)[e:e + 1],
                np.asarray(first.agent_state.h)[e:e + 1])

        rng = np.random.RandomState(0)
        popped = []
        for k, traj in enumerate(reference):
            next_state = (reference[k + 1].agent_state
                          if k + 1 < len(reference)
                          else AgentState(
                              c=np.zeros((B, agent.core_size),
                                         np.float32),
                              h=np.zeros((B, agent.core_size),
                                         np.float32)))
            for t, order in enumerate(_replay_order(T, B, rng),
                                      start=1):
                for e in order:
                    need_state = packer.stage_inference(
                        e, _row(traj.agent_outputs, t, e))
                    assert need_state == (t == T)
                    if need_state:
                        packer.stage_state(
                            e,
                            np.asarray(next_state.c)[e:e + 1],
                            np.asarray(next_state.h)[e:e + 1])
                    completed = packer.add_env(
                        e, _row(traj.env_outputs, t, e))
                    assert completed == (t == T)
            assert packer.ready()
            popped.append(packer.pop())
            assert not packer.ready()

        for k, (birth, state, env_outputs, agent_outputs) in enumerate(
                popped):
            assert birth > 0
            assert_trees_equal(env_outputs, reference[k].env_outputs,
                               msg=f"env_outputs diverge at unroll {k}")
            assert_trees_equal(agent_outputs,
                               reference[k].agent_outputs,
                               msg=f"agent_outputs diverge at unroll {k}")
            np.testing.assert_array_equal(
                state.c, np.asarray(reference[k].agent_state.c))
            np.testing.assert_array_equal(
                state.h, np.asarray(reference[k].agent_state.h))

    def _synthetic_step(self, packer, lane, value):
        agent_row = np.full((1, 2), value, np.float32)
        need = packer.stage_inference(lane, agent_row)
        if need:
            packer.stage_state(lane, np.zeros((1, 3), np.float32),
                               np.zeros((1, 3), np.float32))
        return packer.add_env(lane, np.full((1,), value, np.float32))

    def test_straggler_buffers_without_stalling_siblings(self):
        """Lane 0 runs two full unrolls ahead; its output parks in the
        completed buffer (no error, no emission) until lane 1 catches
        up — then batches pop oldest-first."""
        packer = TrajectoryPacker([1, 1], unroll_length=2)
        for lane in (0, 1):
            packer.bootstrap(lane, np.full((1,), -1.0, np.float32),
                             np.full((1, 2), -1.0, np.float32),
                             np.zeros((1, 3), np.float32),
                             np.zeros((1, 3), np.float32))
        value = 0.0
        for _ in range(2):  # two full unrolls on lane 0 only
            for _ in range(2):
                value += 1.0
                self._synthetic_step(packer, 0, value)
        assert packer.completed_depth(0) == 2
        assert packer.completed_depth(1) == 0
        assert not packer.ready()
        for step in range(2):  # lane 1 catches up one unroll
            self._synthetic_step(packer, 1, 100.0 + step)
        assert packer.ready()
        _, _, env_outputs, _ = packer.pop()
        # Oldest lane-0 unroll paired with lane 1's: [T+1, 2] values.
        np.testing.assert_array_equal(
            env_outputs[:, 0], np.asarray([-1.0, 1.0, 2.0], np.float32))
        np.testing.assert_array_equal(
            env_outputs[:, 1],
            np.asarray([-1.0, 100.0, 101.0], np.float32))
        assert packer.completed_depth(0) == 1
        assert not packer.ready()

    def test_protocol_violations_raise(self):
        packer = TrajectoryPacker([1], unroll_length=2)
        packer.bootstrap(0, np.zeros((1,)), np.zeros((1, 2)),
                         np.zeros((1, 3)), np.zeros((1, 3)))
        with pytest.raises(RuntimeError, match="no staged inference"):
            packer.add_env(0, np.zeros((1,)))
        packer.stage_inference(0, np.zeros((1, 2)))
        with pytest.raises(RuntimeError, match="second inference"):
            packer.stage_inference(0, np.zeros((1, 2)))

    def test_reset_drops_partials_and_buffered_unrolls(self):
        packer = TrajectoryPacker([1, 1], unroll_length=2)
        for lane in (0, 1):
            packer.bootstrap(lane, np.zeros((1,)), np.zeros((1, 2)),
                             np.zeros((1, 3)), np.zeros((1, 3)))
        self._synthetic_step(packer, 0, 1.0)
        packer.reset()
        assert packer.completed_depth(0) == 0
        assert packer.entry_count(0) == 0
        # A fresh bootstrap is required (and sufficient) after reset.
        packer.bootstrap(0, np.zeros((1,)), np.zeros((1, 2)),
                         np.zeros((1, 3)), np.zeros((1, 3)))
        assert packer.entry_count(0) == 1


# ---------------------------------------------------------------------------
# MultiEnv per-worker async protocol
# ---------------------------------------------------------------------------


class TestWorkerAPI:
    def test_worker_slices_cover_the_batch(self):
        envs = make_envs(n=5, workers=2)
        try:
            slices = envs.worker_slices()
            assert envs.num_workers == 2
            assert [s.start for s in slices] == [0, 3]
            assert [s.stop for s in slices] == [3, 5]
        finally:
            envs.close()

    def test_per_worker_steps_match_lockstep(self):
        """The same seeds stepped per-worker must produce exactly the
        lockstep path's outputs, slice by slice."""
        lockstep = make_envs(seed_base=11)
        perworker = make_envs(seed_base=11)
        try:
            ref = lockstep.initial()
            outs = [perworker.worker_initial(w)
                    for w in range(perworker.num_workers)]
            for w, sl in enumerate(perworker.worker_slices()):
                np.testing.assert_array_equal(
                    outs[w].observation.frame,
                    ref.observation.frame[sl])
            actions = np.zeros((B,), np.int32)
            for step in range(3):
                lockstep.step_send(actions)
                ref = lockstep.step_recv()
                for w, sl in enumerate(perworker.worker_slices()):
                    perworker.worker_send(w, actions[sl])
                for w, sl in enumerate(perworker.worker_slices()):
                    out = perworker.worker_recv(w)
                    np.testing.assert_array_equal(
                        out.observation.frame, ref.observation.frame[sl],
                        err_msg=f"step {step} worker {w}")
                    np.testing.assert_array_equal(out.reward,
                                                  ref.reward[sl])
                    np.testing.assert_array_equal(out.done,
                                                  ref.done[sl])
        finally:
            lockstep.close()
            perworker.close()

    def test_dead_worker_respawns_on_per_worker_path(self):
        envs = make_envs()
        try:
            for w in range(envs.num_workers):
                envs.worker_initial(w)
            envs._procs[0].kill()
            envs._procs[0].join(timeout=5)
            envs.worker_send(0, np.zeros((2,), np.int32))
            out = envs.worker_recv(0)
            # The respawned slice restarts with initial outputs:
            # done=True marks the boundary, no episode stats recorded.
            assert out.done.all()
            np.testing.assert_array_equal(
                out.info.episode_step, np.zeros((2,), np.int32))
        finally:
            envs.close()


# ---------------------------------------------------------------------------
# The live service
# ---------------------------------------------------------------------------


def _make_service(agent, groups=2, max_batch=0, **kwargs):
    env_groups = [make_envs(seed_base=100 * g) for g in range(groups)]
    return ActorService(agent, env_groups, T, level_name="fake_small",
                        seed=3, max_batch=max_batch, **kwargs)


class TestActorService:
    def test_emits_learner_shaped_trajectories(self, agent_and_params):
        agent, params = agent_and_params
        service = _make_service(agent)
        service.set_params(params)
        service.start()
        try:
            for _ in range(3):
                out = service.get_trajectory(timeout=120)
                assert out.env_outputs.observation.frame.shape == (
                    T + 1, B, 16, 16, 3)
                assert out.agent_outputs.policy_logits.shape == (
                    T + 1, B, NUM_ACTIONS)
                assert out.agent_state.c.shape == (B, agent.core_size)
                assert out.env_outputs.done.dtype == bool
                assert out.agent_outputs.action.dtype == np.int32
        finally:
            service.stop()

    def test_rejects_max_batch_below_widest_slice(self, agent_and_params):
        agent, _ = agent_and_params
        with pytest.raises(ValueError, match="widest worker slice"):
            _make_service(agent, groups=1, max_batch=1)

    def test_idle_worker_death_rebootstraps_lane_only(self,
                                                      agent_and_params):
        """A reply landing with NO inference staged (the worker died
        idle — request parked in the ring — and worker_recv respawned
        it) must recover at lane granularity: stale request invalidated
        via the lane generation, lane re-bootstrapped, siblings and the
        group restart budget untouched."""
        agent, params = agent_and_params
        service = _make_service(agent, groups=1)
        service.set_params(params)
        try:
            group = service._groups[0]
            for w in range(group.envs.num_workers):
                service._bootstrap_lane(0, w, group.envs.worker_initial(w))
            gen_before = group.lane_gen[0]
            sibling_gen = group.lane_gen[1]
            ring_before = len(service._ring)
            assert not group.packer.has_staged(0)
            out = group.envs.worker_initial(0)  # the respawned reply
            service._handle_reply(0, 0, out)
            assert group.lane_gen[0] == gen_before + 1
            assert group.lane_gen[1] == sibling_gen
            assert group.packer.entry_count(0) == 1  # fresh entry 0
            assert group.packer.entry_count(1) == 1  # sibling untouched
            assert len(service._ring) == ring_before + 1
            # The stale parked request no longer matches the lane gen,
            # so the inference thread will discard instead of dispatch.
            stale = service._ring[0]
            assert (stale.worker, stale.lane_gen) == (0, gen_before)
            assert stale.lane_gen != group.lane_gen[0]
        finally:
            service.stop()

    def test_worker_kill_chaos_respawns_midunroll(self, agent_and_params):
        """A worker SIGKILLed mid-unroll: the per-worker respawn
        substitutes initial outputs (done=True boundary), the packer
        keeps its layout, and trajectories keep flowing."""
        from scalable_agent_tpu.obs import get_registry
        from scalable_agent_tpu.runtime import configure_faults

        agent, params = agent_and_params
        respawns = get_registry().counter("env/worker_respawns_total")
        before = respawns.value
        configure_faults("worker_kill@2")
        try:
            service = _make_service(agent, groups=1)
            service.set_params(params)
            service.start()
            try:
                for _ in range(4):
                    out = service.get_trajectory(timeout=120)
                    assert out.env_outputs.observation.frame.shape == (
                        T + 1, B, 16, 16, 3)
            finally:
                service.stop()
        finally:
            configure_faults("")
        assert respawns.value >= before + 1

    def test_service_stall_chaos_trips_watchdog(self, agent_and_params,
                                                monkeypatch):
        """ISSUE 10 satellite: a wedged inference thread must go STALE
        on the watchdog (forensics instead of silent learner
        starvation) — and the run must recover once the stall ends."""
        from scalable_agent_tpu.obs import configure_watchdog, get_registry
        from scalable_agent_tpu.obs.registry import MetricsRegistry
        from scalable_agent_tpu.runtime import configure_faults

        monkeypatch.setenv("SCALABLE_AGENT_SERVICE_STALL_S", "1.5")
        # PRIVATE registry for the watchdog: its stalls counter must not
        # leak into later tests' prom snapshots (test_obs_smoke asserts
        # a healthy run reads watchdog/stalls_total 0.0 off the global).
        registry = MetricsRegistry()
        stalls = registry.counter("watchdog/stalls_total")
        injected = get_registry().counter("faults/injected_total")
        stalls_before = stalls.value
        injected_before = injected.value
        configure_faults("service_stall@2")
        configure_watchdog(0.3, registry=registry)
        try:
            service = _make_service(agent_and_params[0], groups=1)
            service.set_params(agent_and_params[1])
            service.start()
            try:
                out = service.get_trajectory(timeout=180)
                assert out.env_outputs.observation.frame.shape[0] == T + 1
                deadline = time.monotonic() + 30
                while (stalls.value <= stalls_before
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
            finally:
                service.stop()
        finally:
            configure_watchdog(None)
            configure_faults("")
        assert injected.value >= injected_before + 1
        assert stalls.value >= stalls_before + 1


# ---------------------------------------------------------------------------
# Tier-1 driver smoke (ISSUE 10 acceptance): --actor=service end-to-end
# ---------------------------------------------------------------------------


def test_driver_smoke_actor_service_ledger_complete(tmp_path,
                                                    monkeypatch,
                                                    capsys):
    from scalable_agent_tpu.config import Config
    from scalable_agent_tpu.driver import train as run_train
    from scalable_agent_tpu.obs import get_registry, report
    from scalable_agent_tpu.obs.ledger import SEGMENTS

    monkeypatch.setenv("SCALABLE_AGENT_LEDGER_MFU_PEAK", "1e12")
    config = Config(
        mode="train",
        logdir=str(tmp_path / "run"),
        level_name="fake_small",
        num_actors=4,
        batch_size=2,
        unroll_length=4,
        num_action_repeats=1,
        total_environment_frames=32,  # 4 updates of 8 frames
        height=16,
        width=16,
        num_env_workers_per_group=2,
        compute_dtype="float32",
        checkpoint_interval_s=1e9,
        log_interval_s=0.0,
        seed=5,
        actor="service",
    )

    def _counters():
        snap = get_registry().snapshot()
        return {key: snap.get(f"ledger/trajectories_{key}_total", 0.0)
                for key in ("opened", "retired", "discarded",
                            "abandoned")}

    before = _counters()
    metrics = run_train(config)
    assert metrics["env_frames"] == 32
    delta = {key: value - before[key]
             for key, value in _counters().items()}

    # Complete ledger artifact: zero open records, conservation, every
    # hand-off stage crossed.
    paths = glob.glob(os.path.join(config.logdir, "ledger.p0.json"))
    assert len(paths) == 1, paths
    artifact = json.load(open(paths[0]))
    assert artifact["open_records"] == []
    assert delta["retired"] >= 4
    assert delta["opened"] == (delta["retired"] + delta["discarded"]
                               + delta["abandoned"])
    stages_seen = {e["stage"] for e in artifact["ring_tail"]}
    for stage in ("birth", "unroll_done", "queue_put", "queue_get",
                  "put_done", "dispatch", "retire"):
        assert stage in stages_seen, stage

    # The new service stages publish through the registry/prom plane.
    text = open(os.path.join(config.logdir, "metrics.prom")).read()
    assert "impala_ledger_rho_service_batch" in text
    assert "impala_ledger_rho_service_wait" in text
    assert "impala_service_batch_s_count" in text
    values = {}
    for line in text.splitlines():
        if line.startswith("impala_") and " " in line \
                and not line.startswith("#"):
            key, _, value = line.rpartition(" ")
            try:
                values[key] = float(value)
            except ValueError:
                pass
    assert values["impala_ledger_open_records"] == 0.0
    assert values["impala_service_batches_total"] > 0.0
    shares = {name: values[f"impala_ledger_latency_share_{name}"]
              for name, _, _ in SEGMENTS}
    assert sum(shares.values()) == pytest.approx(1.0, abs=1e-6)

    # The report CLI renders the service rows and a dominant stage.
    assert report.main([config.logdir]) == 0
    out = capsys.readouterr().out
    assert "service_batch" in out
    assert "dominant stage:" in out
    assert "top recommendation:" in out


def test_ingraph_rejects_actor_service(tmp_path):
    from scalable_agent_tpu.config import Config
    from scalable_agent_tpu.driver import train as run_train

    config = Config(mode="train", logdir=str(tmp_path / "run"),
                    level_name="fake_small", train_backend="ingraph",
                    actor="service")
    with pytest.raises(ValueError, match="no host actor pipeline"):
        run_train(config)
