"""Topology-agnostic checkpoint restore (ISSUE 6).

The on-disk checkpoint is host-gathered and fully replicated, so the
SAME bytes must round-trip a TrainState across different mesh shapes:
save sharded over a data=4 mesh, restore onto data=2 (and back up),
with bit-exact params after gather and the per-leaf CRC manifest
verifying AFTER the reshard (``CheckpointManager.verify_after_reshard``
— the check the elastic supervisor's resharded relaunches lean on).
Runs entirely on the virtual 8-device CPU mesh.
"""

import json
import os

import jax
import numpy as np
import pytest

from scalable_agent_tpu.models import ImpalaAgent
from scalable_agent_tpu.parallel import MeshSpec, make_mesh
from scalable_agent_tpu.runtime import (
    Learner,
    LearnerHyperparams,
    Trajectory,
)
from scalable_agent_tpu.runtime import checkpoint as checkpoint_mod
from scalable_agent_tpu.runtime.checkpoint import (
    CheckpointIntegrityError,
    CheckpointManager,
)
from scalable_agent_tpu.types import (
    AgentOutput,
    AgentState,
    Observation,
    StepOutput,
    StepOutputInfo,
)

NUM_ACTIONS = 4
T_PLUS_1 = 2
B = 8  # divides every data-axis size used here (1, 2, 4, 8)


def zero_trajectory(agent, batch=B):
    def zeros(shape, dtype):
        return np.zeros((T_PLUS_1, batch) + tuple(shape), dtype)

    return Trajectory(
        agent_state=AgentState(
            c=np.zeros((batch, 256), np.float32),
            h=np.zeros((batch, 256), np.float32)),
        env_outputs=StepOutput(
            reward=zeros((), np.float32),
            info=StepOutputInfo(
                episode_return=zeros((), np.float32),
                episode_step=zeros((), np.int32)),
            done=zeros((), bool),
            observation=Observation(
                frame=zeros((8, 8, 3), np.uint8), instruction=None),
        ),
        agent_outputs=AgentOutput(
            action=zeros((), np.int32),
            policy_logits=zeros((agent.num_logits,), np.float32),
            baseline=zeros((), np.float32)),
    )


def make_learner(agent, data, **kwargs):
    mesh = make_mesh(MeshSpec(data=data, model=1),
                     devices=jax.devices()[:data])
    return Learner(agent, LearnerHyperparams(
        total_environment_frames=1e6), mesh,
        frames_per_update=T_PLUS_1 * B, **kwargs)


def host_tree(state):
    return jax.tree_util.tree_map(checkpoint_mod._to_host, state)


def assert_trees_bit_exact(a, b):
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    assert len(leaves_a) == len(leaves_b)
    for la, lb in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


@pytest.fixture(scope="module")
def agent():
    return ImpalaAgent(num_actions=NUM_ACTIONS)


@pytest.mark.parametrize("save_data,restore_data", [(4, 2), (2, 4)])
def test_restore_across_shard_counts_is_bit_exact(
        tmp_path, agent, save_data, restore_data):
    logdir = str(tmp_path / f"run_{save_data}to{restore_data}")
    saver = make_learner(agent, save_data)
    state = saver.init(jax.random.key(7), zero_trajectory(agent),
                       env_frames=480.0)
    saved_host = host_tree(state)
    ckpt = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        assert ckpt.maybe_save(3, state, force=True)
        ckpt.wait()
    finally:
        ckpt.close()

    # Fresh manager + a DIFFERENT mesh shape, as an elastic relaunch
    # would construct them.
    restorer = make_learner(agent, restore_data)
    template = restorer.init(jax.random.key(0), zero_trajectory(agent))
    ckpt2 = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        restored = ckpt2.restore(target=template)
        assert restored is not None
        step, host_state = restored
        assert step == 3
        placed = restorer.place_state(host_state)
        # Every leaf landed on the NEW mesh...
        for leaf in jax.tree_util.tree_leaves(placed):
            assert leaf.sharding.mesh.devices.size == restore_data
        # ...and gathers back bit-exact against what was saved.
        assert_trees_bit_exact(host_tree(placed), saved_host)
        assert float(np.asarray(placed.env_frames)) == 480.0
        # The manifest verifies AFTER the reshard (force: the CPU
        # rig's global device count never changes, so the recorded
        # topology alone cannot trigger it — the detection path has
        # its own test below).
        assert ckpt2.verify_after_reshard(3, placed, force=True)
    finally:
        ckpt2.close()


@pytest.mark.parametrize("save_data,restore_data", [(4, 2)])
def test_impact_restore_across_shard_counts_is_bit_exact(
        tmp_path, agent, save_data, restore_data):
    """ISSUE 13 satellite: an ``--loss=impact`` run's TrainState (the
    target network riding in ``target_params``) round-trips a topology
    change bit-exactly, manifest verified after the reshard."""
    logdir = str(tmp_path / "impact_reshard")
    saver = make_learner(agent, save_data, loss="impact")
    state = saver.init(jax.random.key(7), zero_trajectory(agent),
                       env_frames=480.0)
    # Move the online params away from the target so the round trip
    # proves the TWO trees restore independently (a fresh init has
    # target == params, which would hide a crossed-wire restore).
    state, _ = saver.update(
        state, saver.put_trajectory(zero_trajectory(agent)))
    assert state.target_params is not None
    saved_host = host_tree(state)
    ckpt = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        assert ckpt.maybe_save(4, state, force=True)
        ckpt.wait()
    finally:
        ckpt.close()

    restorer = make_learner(agent, restore_data, loss="impact")
    template = restorer.init(jax.random.key(0), zero_trajectory(agent))
    ckpt2 = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        restored = ckpt2.restore(target=template)
        assert restored is not None
        step, host_state = restored
        assert step == 4
        placed = restorer.place_state(host_state)
        for leaf in jax.tree_util.tree_leaves(placed):
            assert leaf.sharding.mesh.devices.size == restore_data
        assert_trees_bit_exact(host_tree(placed), saved_host)
        # The target net specifically survived — and is NOT the online
        # params (the update above moved them apart).
        assert_trees_bit_exact(host_tree(placed.target_params),
                               host_tree(state.target_params))
        different = any(
            not np.array_equal(np.asarray(p), np.asarray(t))
            for p, t in zip(
                jax.tree_util.tree_leaves(placed.params),
                jax.tree_util.tree_leaves(placed.target_params)))
        assert different
        assert ckpt2.verify_after_reshard(4, placed, force=True)
    finally:
        ckpt2.close()


def test_pre_impact_checkpoint_initializes_target_from_online(
        tmp_path, agent):
    """Checkpoint migration (the PR 4 legacy-retry pattern): a
    ``--loss=vtrace`` checkpoint (target_params=None on disk) restored
    into an ``--loss=impact`` run comes up with the target network
    initialized from the restored ONLINE params at place_state time."""
    logdir = str(tmp_path / "vtrace_to_impact")
    saver = make_learner(agent, 2)            # vtrace: no target net
    state = saver.init(jax.random.key(5), zero_trajectory(agent),
                       env_frames=96.0)
    assert state.target_params is None
    saved_params = host_tree(state.params)
    ckpt = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        assert ckpt.maybe_save(2, state, force=True)
        ckpt.wait()
    finally:
        ckpt.close()

    impact_learner = make_learner(agent, 2, loss="impact")
    template = impact_learner.init(jax.random.key(0),
                                   zero_trajectory(agent))
    assert template.target_params is not None
    ckpt2 = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        restored = ckpt2.restore(target=template)
        assert restored is not None
        _, host_state = restored
        placed = impact_learner.place_state(host_state)
        # The migrated target net IS the restored online params.
        assert placed.target_params is not None
        assert_trees_bit_exact(host_tree(placed.params), saved_params)
        assert_trees_bit_exact(host_tree(placed.target_params),
                               saved_params)
        assert float(np.asarray(placed.env_frames)) == 96.0
    finally:
        ckpt2.close()


def test_impact_checkpoint_restores_into_vtrace_run(tmp_path, agent):
    """The reverse crossing: an ``--loss=impact`` checkpoint restored
    under ``--loss=vtrace`` carries the target net through untouched
    (the vtrace update ignores it) — nothing is silently dropped."""
    logdir = str(tmp_path / "impact_to_vtrace")
    saver = make_learner(agent, 2, loss="impact")
    state = saver.init(jax.random.key(6), zero_trajectory(agent))
    saved_target = host_tree(state.target_params)
    ckpt = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        assert ckpt.maybe_save(3, state, force=True)
        ckpt.wait()
    finally:
        ckpt.close()

    vtrace_learner = make_learner(agent, 2)
    template = vtrace_learner.init(jax.random.key(0),
                                   zero_trajectory(agent))
    assert template.target_params is None
    ckpt2 = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        restored = ckpt2.restore(target=template)
        assert restored is not None
        _, host_state = restored
        assert host_state.target_params is not None
        assert_trees_bit_exact(host_state.target_params, saved_target)
    finally:
        ckpt2.close()


def test_manifest_records_topology(tmp_path, agent):
    logdir = str(tmp_path / "topo")
    learner = make_learner(agent, 2)
    state = learner.init(jax.random.key(1), zero_trajectory(agent))
    ckpt = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        assert ckpt.maybe_save(1, state, force=True)
        ckpt.wait()
        manifest = json.load(open(os.path.join(
            logdir, "checkpoints", "manifests", "1.json")))
        assert manifest["topology"] == {
            "num_processes": 1,
            "num_devices": len(jax.devices()),
        }
        assert ckpt.saved_topology(1) == manifest["topology"]
        assert ckpt.saved_topology(99) is None
    finally:
        ckpt.close()


def test_topology_change_is_detected_and_counted(
        tmp_path, agent, monkeypatch):
    logdir = str(tmp_path / "detect")
    learner = make_learner(agent, 2)
    state = learner.init(jax.random.key(2), zero_trajectory(agent))
    ckpt = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        assert ckpt.maybe_save(5, state, force=True)
        ckpt.wait()
        # Same layout: a no-op, no verification paid.
        assert not ckpt.verify_after_reshard(5, state)
        # Simulate an elastic relaunch that lost a host: the global
        # device count this process sees has changed.
        monkeypatch.setattr(checkpoint_mod.jax, "device_count",
                            lambda: 4)
        assert ckpt.verify_after_reshard(5, state)
    finally:
        monkeypatch.undo()
        ckpt.close()


def test_resharded_state_mismatch_raises(tmp_path, agent):
    logdir = str(tmp_path / "mismatch")
    learner = make_learner(agent, 2)
    state = learner.init(jax.random.key(3), zero_trajectory(agent))
    ckpt = CheckpointManager(logdir, interval_s=1e9, keep=3)
    try:
        assert ckpt.maybe_save(2, state, force=True)
        ckpt.wait()
        # A state that is NOT what the manifest describes (different
        # seed) must fail the post-reshard verification loudly.
        other = learner.init(jax.random.key(99), zero_trajectory(agent))
        with pytest.raises(CheckpointIntegrityError,
                           match="after resharding"):
            ckpt.verify_after_reshard(2, other, force=True)
    finally:
        ckpt.close()
