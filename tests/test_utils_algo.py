"""Algorithm utilities (utils/algo.py) + Doom tooling helpers."""

import numpy as np
import pytest

from scalable_agent_tpu.utils.algo import (
    RunningMeanStd,
    calculate_gae,
    discounted_sums,
    num_env_steps,
)


class TestRunningMeanStd:
    def test_matches_batch_statistics(self):
        rng = np.random.default_rng(0)
        data = rng.standard_normal((500, 3)) * 2.5 + 1.0
        rms = RunningMeanStd(shape=(3,))
        for chunk in np.split(data, 10):
            rms.update(chunk)
        np.testing.assert_allclose(rms.mean, data.mean(axis=0), atol=1e-2)
        np.testing.assert_allclose(rms.var, data.var(axis=0), rtol=1e-2)

    def test_normalize(self):
        rms = RunningMeanStd()
        rms.update(np.asarray([10.0, 12.0, 8.0, 10.0]))
        normalized = rms.normalize(np.asarray([10.0]))
        assert abs(float(normalized[0])) < 0.5


class TestDiscounting:
    def test_discounted_sums_literal(self):
        out = discounted_sums([1.0, 1.0, 1.0], gamma=0.5)
        np.testing.assert_allclose(out, [1.75, 1.5, 1.0])

    def test_gae_against_literal_expansion(self):
        rewards = [1.0, 0.0, 2.0]
        dones = [False, False, False]
        values = [0.5, 0.4, 0.3, 0.2]
        gamma, lam = 0.9, 0.8
        adv, rets = calculate_gae(rewards, dones, values, gamma, lam)
        deltas = [rewards[t] + gamma * values[t + 1] - values[t]
                  for t in range(3)]
        expected2 = deltas[2]
        expected1 = deltas[1] + gamma * lam * expected2
        expected0 = deltas[0] + gamma * lam * expected1
        np.testing.assert_allclose(adv, [expected0, expected1, expected2])
        np.testing.assert_allclose(rets, adv + np.asarray(values[:3]))

    def test_gae_resets_at_done(self):
        adv_nodone, _ = calculate_gae(
            [1.0, 1.0], [False, False], [0.0, 0.0, 5.0], 0.9, 0.95)
        adv_done, _ = calculate_gae(
            [1.0, 1.0], [True, False], [0.0, 0.0, 5.0], 0.9, 0.95)
        # the done at t=0 cuts off downstream bootstrap/advantage flow
        assert adv_done[0] == pytest.approx(1.0)
        assert adv_nodone[0] > adv_done[0]

    def test_gae_shape_validation(self):
        with pytest.raises(ValueError, match="len\\(rewards\\)\\+1"):
            calculate_gae([1.0], [False], [0.0], 0.9, 0.95)

    def test_num_env_steps(self):
        assert num_env_steps([{"num_frames": 4}, {}, {"num_frames": 2}]) == 7


class TestDoomRenderGrid:
    def test_concat_grid_tiles(self):
        from scalable_agent_tpu.envs.doom.tools import concat_grid

        frames = [np.full((4, 6, 3), i, np.uint8) for i in range(3)]
        grid = concat_grid(frames)
        assert grid.shape == (8, 12, 3)  # 2x2 grid for 3 frames
        assert (grid[:4, :6] == 0).all()
        assert (grid[:4, 6:12] == 1).all()
        assert (grid[4:, :6] == 2).all()
        assert (grid[4:, 6:] == 0).all()  # empty cell
