"""Golden-trajectory regression tests (SURVEY §4's "add what the
reference lacks": fixed-seed FakeEnv trajectories are reproducible
golden data, so any accidental change to env transition semantics —
reward schedule, episode boundaries, frame generation, the ImpalaStream
accounting — fails loudly here instead of silently shifting training
behavior).

The checksums cover frames (sha256 over the raw bytes), the reward sum,
and the done count of a 50-step fixed-action rollout.  They depend only
on numpy (no jax PRNG), so they are stable across jax upgrades.
"""

import hashlib

import numpy as np
import pytest

from scalable_agent_tpu.envs import make_impala_stream

GOLDEN = {
    # name: (frame_sha256_prefix, reward_sum, done_count)
    "fake_small": ("d5af4decf92ab545", 10.0, 5),
    "fake_benchmark": ("5811719a5bea8033", 5.1, 0),
}


@pytest.mark.parametrize("name", sorted(GOLDEN))
def test_golden_trajectory(name):
    want_hash, want_reward, want_dones = GOLDEN[name]
    stream = make_impala_stream(name, seed=7)
    try:
        out = stream.initial()
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(out.observation.frame))
        reward_sum, done_count = 0.0, 0
        for t in range(50):
            out = stream.step(t % 3)
            h.update(np.ascontiguousarray(out.observation.frame))
            reward_sum += float(out.reward)
            done_count += bool(out.done)
        assert h.hexdigest()[:16] == want_hash
        assert reward_sum == pytest.approx(want_reward, abs=1e-4)
        assert done_count == want_dones
    finally:
        stream.close()


def test_golden_is_seed_sensitive():
    """A different seed must change the trajectory — otherwise the
    golden test would not actually pin the seeded stream."""
    stream = make_impala_stream("fake_small", seed=8)
    try:
        out = stream.initial()
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(out.observation.frame))
        for t in range(50):
            out = stream.step(t % 3)
            h.update(np.ascontiguousarray(out.observation.frame))
        assert h.hexdigest()[:16] != GOLDEN["fake_small"][0]
    finally:
        stream.close()
