"""Tuple-categorical distribution tests + composite-action end-to-end.

Ports the semantics of the reference's action distributions (reference:
algorithms/utils/action_distributions.py:49-201) to the pure-function
JAX design, and closes the loop the reference never tests hermetically:
an agent with a Tuple(Discrete, Discretized) policy training through the
full actor->learner path on FakeEnv.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalable_agent_tpu.envs import MultiEnv, create_env, make_impala_stream
from scalable_agent_tpu.envs.spaces import (
    Discrete,
    Discretized,
    TupleSpace,
)
from scalable_agent_tpu.envs.spec import TensorSpec
from scalable_agent_tpu.models import ImpalaAgent
from scalable_agent_tpu.ops import distributions as D
from scalable_agent_tpu.ops import losses, vtrace

SPACE = TupleSpace([Discrete(3), Discretized(5, -1.0, 1.0)])
SPEC = D.spec_for_space(SPACE)


class TestDistributionSpec:
    def test_spec_for_spaces(self):
        assert D.spec_for_space(Discrete(7)).sizes == (7,)
        assert SPEC.sizes == (3, 5)
        assert SPEC.num_logits == 8 and SPEC.num_components == 2
        nested = TupleSpace([SPACE, Discrete(2)])
        assert D.spec_for_space(nested).sizes == (3, 5, 2)

    def test_rejects_box(self):
        from scalable_agent_tpu.envs.spaces import Box

        with pytest.raises(NotImplementedError):
            D.spec_for_space(Box(-1, 1, (2,)))


class TestDistributionMath:
    def test_sample_shapes_and_bounds(self):
        logits = jnp.zeros((4, 8))
        actions = D.sample(jax.random.key(0), logits, SPEC)
        assert actions.shape == (4, 2) and actions.dtype == jnp.int32
        assert np.all(np.asarray(actions[:, 0]) < 3)
        assert np.all(np.asarray(actions[:, 1]) < 5)
        # K == 1 keeps the component-less layout.
        single = D.sample(jax.random.key(0), jnp.zeros((4, 3)),
                          D.spec_for_space(Discrete(3)))
        assert single.shape == (4,)

    def test_log_prob_is_sum_of_components(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((6, 8)), jnp.float32)
        actions = jnp.asarray(
            np.stack([rng.integers(0, 3, 6), rng.integers(0, 5, 6)], -1),
            jnp.int32)
        joint = D.log_prob(logits, actions, SPEC)
        lp0 = jax.nn.log_softmax(logits[:, :3])[
            np.arange(6), actions[:, 0]]
        lp1 = jax.nn.log_softmax(logits[:, 3:])[
            np.arange(6), actions[:, 1]]
        np.testing.assert_allclose(joint, lp0 + lp1, rtol=1e-6)

    def test_entropy_uniform(self):
        # Uniform over each component: H = log 3 + log 5.
        ent = D.entropy(jnp.zeros((2, 8)), SPEC)
        np.testing.assert_allclose(
            ent, np.log(3) + np.log(5), rtol=1e-6)

    def test_kl(self):
        rng = np.random.default_rng(1)
        p = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
        np.testing.assert_allclose(
            D.kl_divergence(p, p, SPEC), 0.0, atol=1e-6)
        q = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
        assert np.all(np.asarray(D.kl_divergence(p, q, SPEC)) > 0)

    def test_symmetric_kl(self):
        """Symmetric KL: zero at p == q, symmetric in its arguments,
        and the mean of the two directed KLs (reference:
        action_distributions.py:84-108)."""
        rng = np.random.default_rng(4)
        p = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
        q = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
        np.testing.assert_allclose(
            D.symmetric_kl(p, p, SPEC), 0.0, atol=1e-6)
        np.testing.assert_allclose(
            D.symmetric_kl(p, q, SPEC), D.symmetric_kl(q, p, SPEC),
            rtol=1e-6)
        np.testing.assert_allclose(
            D.symmetric_kl(p, q, SPEC),
            0.5 * (D.kl_divergence(p, q, SPEC)
                   + D.kl_divergence(q, p, SPEC)),
            rtol=1e-6)

    def test_kl_to_prior(self):
        """Uniform policy has zero KL to the uniform prior; any peaked
        policy has positive KL (reference: kl_prior,
        action_distributions.py:95-98,187-191)."""
        np.testing.assert_allclose(
            D.kl_to_prior(jnp.zeros((2, 8)), SPEC), 0.0, atol=1e-6)
        peaked = jnp.zeros((1, 8)).at[0, 0].set(10.0)
        assert float(D.kl_to_prior(peaked, SPEC)[0]) > 0
        # Decomposes as the sum over independent components.
        rng = np.random.default_rng(5)
        logits = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
        per_component = (
            D.kl_to_prior(logits[:, :3], D.spec_for_space(Discrete(3)))
            + D.kl_to_prior(logits[:, 3:], D.spec_for_space(Discrete(5))))
        np.testing.assert_allclose(
            D.kl_to_prior(logits, SPEC), per_component, rtol=1e-6)

    def test_one_hot_actions_layout(self):
        actions = jnp.asarray([[1, 4]], jnp.int32)
        one_hot = D.one_hot_actions(actions, SPEC)
        np.testing.assert_array_equal(
            one_hot[0], [0, 1, 0, 0, 0, 0, 0, 1])

    def test_losses_accept_composite(self):
        rng = np.random.default_rng(2)
        logits = jnp.asarray(rng.standard_normal((4, 2, 8)), jnp.float32)
        actions = jnp.asarray(
            np.stack([rng.integers(0, 3, (4, 2)),
                      rng.integers(0, 5, (4, 2))], -1), jnp.int32)
        advantages = jnp.ones((4, 2))
        pg = losses.compute_policy_gradient_loss(
            logits, actions, advantages, dist_spec=SPEC)
        expected = -jnp.sum(D.log_prob(logits, actions, SPEC))
        np.testing.assert_allclose(pg, expected, rtol=1e-6)
        ent = losses.compute_entropy_loss(logits, dist_spec=SPEC)
        np.testing.assert_allclose(
            ent, -jnp.sum(D.entropy(logits, SPEC)), rtol=1e-6)

    def test_vtrace_composite_log_rhos(self):
        """Composite V-trace log-rhos == sum of per-component ratios."""
        rng = np.random.default_rng(3)
        T, B = 5, 2
        behaviour = jnp.asarray(
            rng.standard_normal((T, B, 8)), jnp.float32)
        target = jnp.asarray(rng.standard_normal((T, B, 8)), jnp.float32)
        actions = jnp.asarray(
            np.stack([rng.integers(0, 3, (T, B)),
                      rng.integers(0, 5, (T, B))], -1), jnp.int32)
        out = vtrace.from_logits(
            behaviour_policy_logits=behaviour,
            target_policy_logits=target,
            actions=actions,
            discounts=jnp.full((T, B), 0.9),
            rewards=jnp.asarray(rng.standard_normal((T, B)), jnp.float32),
            values=jnp.asarray(rng.standard_normal((T, B)), jnp.float32),
            bootstrap_value=jnp.zeros((B,)),
            dist_spec=SPEC)
        expected = (D.log_prob(target, actions, SPEC)
                    - D.log_prob(behaviour, actions, SPEC))
        np.testing.assert_allclose(out.log_rhos, expected, rtol=1e-5)


@pytest.mark.slow
class TestCompositeEndToEnd:
    def test_learner_trains_on_tuple_space(self):
        """Full actor->learner loop on FakeEnv with a
        Tuple(Discrete, Discretized) action space (the VERDICT r1
        done-criterion for composite actions)."""
        from scalable_agent_tpu.parallel import MeshSpec, make_mesh
        from scalable_agent_tpu.runtime import (
            ActorPool, Learner, LearnerHyperparams, Trajectory)

        T, B = 4, 4
        env = create_env("fake_tuple")
        agent = ImpalaAgent(action_space=env.action_space)
        env.close()
        assert agent.num_logits == 8 and agent.num_action_components == 2

        frame = TensorSpec((16, 16, 3), np.uint8, "frame")
        fns = [functools.partial(make_impala_stream, "fake_tuple", seed=i)
               for i in range(B)]
        groups = [MultiEnv(fns, frame, num_workers=2)]
        mesh = make_mesh(MeshSpec(data=4, model=1),
                         devices=jax.devices()[:4])
        learner = Learner(agent, LearnerHyperparams(), mesh,
                          frames_per_update=T * B)
        pool = ActorPool(agent, groups, unroll_length=T, seed=21)
        try:
            # Bootstrap params from one trajectory's shapes.
            actor = pool._actors[0]
            actor._bootstrap(None)
            params = agent.init(
                jax.random.key(0),
                np.asarray(agent.zero_actions(B))[None],
                jax.tree_util.tree_map(
                    lambda x: None if x is None else np.asarray(x)[None],
                    actor._last_env_output,
                    is_leaf=lambda x: x is None),
                actor._core_state)
            pool.set_params(params)
            pool.start()
            state = None
            for _ in range(3):
                out = pool.get_trajectory(timeout=120)
                assert out.agent_outputs.action.shape == (T + 1, B, 2)
                traj = Trajectory(out.agent_state, out.env_outputs,
                                  out.agent_outputs)
                if state is None:
                    state = learner.init(jax.random.key(1), traj)
                state, metrics = learner.update(
                    state, learner.put_trajectory(traj))
                pool.set_params(state.params)
            assert np.isfinite(float(metrics["total_loss"]))
        finally:
            pool.stop()
