"""Pallas grad-W stem kernel (ops/conv_pallas.py): parity against
XLA's own derivative across geometry edges, the K % S fallback, the
bf16 MXU-operand mode, batch-tile padding, and checkpoint
interchangeability of the agent-facing PallasStemConv module.

All CPU runs go through the Pallas interpreter (the same kernel body
TPU compiles), so tier-1 exercises the real code path — the
ops/lstm_pallas.py testing contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalable_agent_tpu.ops import conv_pallas

_INTERPRET = jax.default_backend() != "tpu"


def _conv(x, w, s):
    return jax.lax.conv_general_dilated(
        x, w, (s, s), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _reference_gradw(x, cot, k, s):
    """XLA's own d/dW of the SAME conv under cotangent ``cot`` — the
    derivative the Pallas kernel must reproduce."""
    w0 = jnp.zeros((k, k, x.shape[-1], cot.shape[-1]), jnp.float32)
    return jax.grad(lambda w: jnp.sum(_conv(x, w, s) * cot))(w0)


def _random_case(seed, n, h, w, c, f, s):
    kx, kg = jax.random.split(jax.random.key(seed))
    out_h, out_w = -(-h // s), -(-w // s)
    x = jax.random.normal(kx, (n, h, w, c), jnp.float32)
    g = jax.random.normal(kg, (n, out_h, out_w, f), jnp.float32)
    return x, g


# (h, w, k, s): the stem aspect at reduced size, odd spatial extents
# (asymmetric SAME padding on both axes), a smaller stem, stride ==
# kernel (depth-1 tiles, no overlap), and the 1x1 degenerate case.
GEOMETRIES = (
    (24, 32, 8, 4),
    (17, 23, 8, 4),
    (9, 11, 4, 2),
    (8, 8, 2, 2),
    (5, 5, 1, 1),
)


class TestGradWParity:
    @pytest.mark.parametrize("h,w,k,s", GEOMETRIES)
    def test_f32_matches_xla_derivative(self, h, w, k, s):
        x, g = _random_case(k * 100 + s, 3, h, w, 3, 8, s)
        dw = conv_pallas.conv_gradw(x, g, k, s, interpret=_INTERPRET)
        ref = _reference_gradw(x, g, k, s)
        assert dw.dtype == jnp.float32
        np.testing.assert_allclose(dw, ref, rtol=2e-5, atol=2e-5)

    def test_bf16_operands_f32_accumulation(self):
        """bf16 MXU operands with the f32 scratch accumulator: the
        documented tolerance is bf16's ~8-bit mantissa on the operands,
        NOT a bf16 accumulation error (which would grow with N*OH*OW
        and blow far past 3e-2 at this size)."""
        x, g = _random_case(7, 4, 24, 32, 3, 8, 4)
        dw = conv_pallas.conv_gradw(x, g, 8, 4, interpret=_INTERPRET,
                                    matmul_dtype="bfloat16")
        ref = _reference_gradw(x, g, 8, 4)
        assert dw.dtype == jnp.float32
        scale = float(jnp.max(jnp.abs(ref)))
        np.testing.assert_allclose(dw, ref, rtol=3e-2,
                                   atol=3e-2 * scale)

    def test_k_not_multiple_of_stride_falls_back_exact(self):
        """K % S != 0 breaks the space-to-depth tap lattice, so the op
        routes to XLA's own derivative — bit-identical by
        construction."""
        x, g = _random_case(11, 3, 10, 13, 3, 8, 2)
        dw = conv_pallas.conv_gradw(x, g, 3, 2, interpret=_INTERPRET)
        ref = _reference_gradw(x, g, 3, 2)
        np.testing.assert_array_equal(np.asarray(dw), np.asarray(ref))

    def test_batch_tile_padding_remainder(self, monkeypatch):
        """N not divisible by the batch tile zero-pads the grid's last
        step; zero cotangent rows contribute exactly zero, so the
        result must not change vs the untiled answer."""
        monkeypatch.setattr(conv_pallas, "_MAX_BATCH_TILE", 2)
        x, g = _random_case(13, 5, 16, 16, 3, 8, 4)
        dw = conv_pallas.conv_gradw(x, g, 8, 4, interpret=_INTERPRET)
        ref = _reference_gradw(x, g, 8, 4)
        np.testing.assert_allclose(dw, ref, rtol=2e-5, atol=2e-5)


class TestStemConvVjp:
    def test_forward_is_xla_conv(self):
        x, _ = _random_case(17, 2, 17, 23, 3, 8, 4)
        w = jax.random.normal(jax.random.key(3), (8, 8, 3, 8),
                              jnp.float32) * 0.05
        out = conv_pallas.stem_conv(x, w, 4, _INTERPRET, "float32")
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(_conv(x, w, 4)))

    def test_value_and_grad_under_jit(self):
        """The full custom_vjp in a jitted value_and_grad over BOTH
        inputs: dx is XLA's transposed conv (exact), dw the Pallas
        kernel (tight f32 tolerance)."""
        x, _ = _random_case(19, 2, 16, 16, 3, 8, 4)
        w = jax.random.normal(jax.random.key(5), (8, 8, 3, 8),
                              jnp.float32) * 0.05

        def loss(op):
            return lambda xx, ww: jnp.sum(op(xx, ww) ** 2)

        pallas_loss = jax.jit(jax.value_and_grad(
            loss(lambda xx, ww: conv_pallas.stem_conv(
                xx, ww, 4, _INTERPRET, "float32")), argnums=(0, 1)))
        xla_loss = jax.jit(jax.value_and_grad(
            loss(lambda xx, ww: _conv(xx, ww, 4)), argnums=(0, 1)))
        val_p, (dx_p, dw_p) = pallas_loss(x, w)
        val_x, (dx_x, dw_x) = xla_loss(x, w)
        np.testing.assert_allclose(val_p, val_x, rtol=1e-6)
        np.testing.assert_allclose(dx_p, dx_x, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(dw_p, dw_x, rtol=2e-5, atol=2e-5)


class TestPallasStemConvModule:
    def _frame(self, seed=23):
        return jax.random.randint(
            jax.random.key(seed), (2, 24, 32, 3), 0, 255, jnp.int32
        ).astype(jnp.uint8)

    def test_checkpoint_interchangeable_with_nn_conv(self):
        """Same param tree (kernel [K,K,C,F] + bias under the module
        name) and the same function of those params — a torso
        checkpoint written by either backend restores into the other
        (the _SpaceToDepthFirstConv contract)."""
        from scalable_agent_tpu.models import networks

        xla = networks.ShallowConvTorso(conv_backend="xla")
        pallas = networks.ShallowConvTorso(conv_backend="pallas")
        frame = self._frame()
        params = xla.init(jax.random.key(0), frame)
        params_p = pallas.init(jax.random.key(0), frame)
        assert (jax.tree_util.tree_structure(params)
                == jax.tree_util.tree_structure(params_p))
        assert (jax.tree_util.tree_map(jnp.shape, params)
                == jax.tree_util.tree_map(jnp.shape, params_p))
        out_x = xla.apply(params, frame)
        out_p = pallas.apply(params, frame)  # the XLA checkpoint
        np.testing.assert_allclose(out_x, out_p, rtol=1e-6, atol=1e-6)

    def test_torso_grads_match_xla_backend(self):
        """End-to-end through the torso: the two backends are the same
        mathematical function, so loss gradients agree to f32 kernel
        tolerance."""
        from scalable_agent_tpu.models import networks

        frame = self._frame(29)
        xla = networks.ShallowConvTorso(conv_backend="xla")
        pallas = networks.ShallowConvTorso(conv_backend="pallas")
        params = xla.init(jax.random.key(1), frame)

        def grads(torso):
            return jax.grad(
                lambda p: jnp.sum(torso.apply(p, frame) ** 2))(params)

        gx, gp = grads(xla), grads(pallas)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=5e-5, atol=5e-5), gx, gp)

    def test_unknown_backend_rejected(self):
        from scalable_agent_tpu.models import networks

        with pytest.raises(ValueError, match="conv_backend"):
            networks.ShallowConvTorso(conv_backend="tensorrt").init(
                jax.random.key(0), self._frame())
