"""V-trace numerical tests against an O(T^2) numpy ground truth.

Mirrors the reference's test strategy (reference: vtrace_test.py:44-83):
the ground truth literally expands the V-trace definition from the paper,
independent of any scan formulation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from scalable_agent_tpu.ops import vtrace


def _shaped_arange(*shape):
    return np.arange(int(np.prod(shape)), dtype=np.float32).reshape(*shape)


def _softmax(x, axis=-1):
    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


def ground_truth_vtrace(log_rhos, discounts, rewards, values, bootstrap_value,
                        clip_rho_threshold, clip_pg_rho_threshold):
    """Literal-notation O(T^2) V-trace computation in numpy."""
    vs = []
    seq_len = len(discounts)
    rhos = np.exp(log_rhos)
    cs = np.minimum(rhos, 1.0)
    clipped_rhos = rhos
    if clip_rho_threshold:
        clipped_rhos = np.minimum(rhos, clip_rho_threshold)
    clipped_pg_rhos = rhos
    if clip_pg_rho_threshold:
        clipped_pg_rhos = np.minimum(rhos, clip_pg_rho_threshold)

    # v_s = V(x_s) + sum_{t=s}^{T-1} gamma^{t-s} * (prod_{i=s}^{t-1} c_i)
    #               * clipped_rho_t * (r_t + gamma V(x_{t+1}) - V(x_t))
    values_t_plus_1 = np.concatenate(
        [values, bootstrap_value[None, :]], axis=0)
    for s in range(seq_len):
        v_s = np.copy(values[s])
        for t in range(s, seq_len):
            v_s += (
                np.prod(discounts[s:t], axis=0)
                * np.prod(cs[s:t], axis=0)
                * clipped_rhos[t]
                * (rewards[t] + discounts[t] * values_t_plus_1[t + 1]
                   - values[t]))
        vs.append(v_s)
    vs = np.stack(vs, axis=0)

    vs_t_plus_1 = np.concatenate([vs[1:], bootstrap_value[None, :]], axis=0)
    pg_advantages = clipped_pg_rhos * (
        rewards + discounts * vs_t_plus_1 - values)
    return vs, pg_advantages


def _make_inputs(seq_len, batch_size, rho_scale=None):
    rng = np.random.RandomState(seq_len * 100 + batch_size)
    if rho_scale is None:
        rho_scale = [10.0, 2.0, 1.0, 0.5, 0.1]
    log_rhos = rng.uniform(-2.5, 2.5, (seq_len, batch_size)).astype(np.float32)
    values = {
        "log_rhos": log_rhos,
        "discounts": (rng.uniform(0.0, 1.0, (seq_len, batch_size))
                      .astype(np.float32) * 0.9),
        "rewards": _shaped_arange(seq_len, batch_size) / 10.0,
        "values": _shaped_arange(seq_len, batch_size) / 100.0,
        "bootstrap_value": _shaped_arange(batch_size) + 1.0,
    }
    return values


@pytest.mark.parametrize("batch_size", [1, 5])
@pytest.mark.parametrize("scan_impl", ["associative", "sequential", "pallas"])
def test_vtrace_matches_ground_truth(batch_size, scan_impl):
    seq_len = 5
    inputs = _make_inputs(seq_len, batch_size)
    clip_rho, clip_pg_rho = 3.7, 2.2

    out = vtrace.from_importance_weights(
        clip_rho_threshold=clip_rho, clip_pg_rho_threshold=clip_pg_rho,
        scan_impl=scan_impl, **inputs)
    gt_vs, gt_pg = ground_truth_vtrace(
        inputs["log_rhos"], inputs["discounts"], inputs["rewards"],
        inputs["values"], inputs["bootstrap_value"], clip_rho, clip_pg_rho)

    np.testing.assert_allclose(gt_vs, np.asarray(out.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        gt_pg, np.asarray(out.pg_advantages), rtol=1e-4, atol=1e-5)


def test_vtrace_no_clipping():
    inputs = _make_inputs(7, 3)
    out = vtrace.from_importance_weights(
        clip_rho_threshold=None, clip_pg_rho_threshold=None, **inputs)
    gt_vs, gt_pg = ground_truth_vtrace(
        inputs["log_rhos"], inputs["discounts"], inputs["rewards"],
        inputs["values"], inputs["bootstrap_value"], None, None)
    np.testing.assert_allclose(gt_vs, np.asarray(out.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        gt_pg, np.asarray(out.pg_advantages), rtol=1e-4, atol=1e-5)


def test_associative_matches_sequential_long_sequence():
    """The parallel scan must agree with the sequential one at T=100."""
    inputs = _make_inputs(100, 4)
    a = vtrace.from_importance_weights(scan_impl="associative", **inputs)
    s = vtrace.from_importance_weights(scan_impl="sequential", **inputs)
    np.testing.assert_allclose(
        np.asarray(a.vs), np.asarray(s.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(a.pg_advantages), np.asarray(s.pg_advantages),
        rtol=1e-4, atol=1e-5)


def test_pallas_t1_edge():
    """T=1 must not emit a zero-size values[1:] slice (Mosaic rejects
    zero-size vectors)."""
    inputs = _make_inputs(1, 8)
    p = vtrace.from_importance_weights(scan_impl="pallas", **inputs)
    s = vtrace.from_importance_weights(scan_impl="sequential", **inputs)
    np.testing.assert_allclose(
        np.asarray(p.vs), np.asarray(s.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(p.pg_advantages), np.asarray(s.pg_advantages),
        rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("batch_size", [4, 128, 200])
def test_pallas_matches_sequential_long_sequence(batch_size):
    """The fused Pallas kernel must agree at T=100 across batch sizes that
    exercise lane padding (4, 200) and the exact-tile case (128)."""
    inputs = _make_inputs(100, batch_size)
    p = vtrace.from_importance_weights(scan_impl="pallas", **inputs)
    s = vtrace.from_importance_weights(scan_impl="sequential", **inputs)
    np.testing.assert_allclose(
        np.asarray(p.vs), np.asarray(s.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(p.pg_advantages), np.asarray(s.pg_advantages),
        rtol=1e-4, atol=1e-5)


def test_pallas_higher_rank_and_no_clipping():
    """Trailing dims flatten into the lane axis; None thresholds disable
    clipping inside the kernel."""
    seq_len, batch_size, c = 4, 2, 3
    rng = np.random.RandomState(3)
    inputs = {
        "log_rhos": rng.uniform(-1, 1, (seq_len, batch_size, c))
                        .astype(np.float32),
        "discounts": np.full((seq_len, batch_size, c), 0.9, np.float32),
        "rewards": _shaped_arange(seq_len, batch_size, c),
        "values": _shaped_arange(seq_len, batch_size, c) / 10.0,
        "bootstrap_value": _shaped_arange(batch_size, c),
    }
    p = vtrace.from_importance_weights(
        scan_impl="pallas", clip_rho_threshold=None,
        clip_pg_rho_threshold=None, **inputs)
    s = vtrace.from_importance_weights(
        scan_impl="sequential", clip_rho_threshold=None,
        clip_pg_rho_threshold=None, **inputs)
    assert p.vs.shape == (seq_len, batch_size, c)
    np.testing.assert_allclose(
        np.asarray(p.vs), np.asarray(s.vs), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(p.pg_advantages), np.asarray(s.pg_advantages),
        rtol=1e-4, atol=1e-5)


def test_higher_rank_inputs():
    """Extra trailing dims, as the reference supports (vtrace.py:176-180)."""
    seq_len, batch_size, c = 4, 2, 3
    rng = np.random.RandomState(0)
    inputs = {
        "log_rhos": rng.uniform(-1, 1, (seq_len, batch_size, c))
                        .astype(np.float32),
        "discounts": np.full((seq_len, batch_size, c), 0.9, np.float32),
        "rewards": _shaped_arange(seq_len, batch_size, c),
        "values": _shaped_arange(seq_len, batch_size, c) / 10.0,
        "bootstrap_value": _shaped_arange(batch_size, c),
    }
    out = vtrace.from_importance_weights(**inputs)
    assert out.vs.shape == (seq_len, batch_size, c)

    # Ground truth computed per trailing index.
    for i in range(c):
        gt_vs, gt_pg = ground_truth_vtrace(
            inputs["log_rhos"][..., i], inputs["discounts"][..., i],
            inputs["rewards"][..., i], inputs["values"][..., i],
            inputs["bootstrap_value"][..., i], 1.0, 1.0)
        np.testing.assert_allclose(
            gt_vs, np.asarray(out.vs[..., i]), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            gt_pg, np.asarray(out.pg_advantages[..., i]),
            rtol=1e-4, atol=1e-5)


def test_rank_mismatch_raises():
    inputs = _make_inputs(5, 2)
    inputs["bootstrap_value"] = np.zeros((2, 3), np.float32)
    with pytest.raises(ValueError):
        vtrace.from_importance_weights(**inputs)


def test_log_probs_from_logits_and_actions():
    seq_len, batch_size, num_actions = 7, 3, 5
    rng = np.random.RandomState(1)
    logits = rng.normal(size=(seq_len, batch_size, num_actions)) \
                .astype(np.float32)
    actions = rng.randint(0, num_actions, (seq_len, batch_size)) \
                 .astype(np.int32)
    out = vtrace.log_probs_from_logits_and_actions(logits, actions)

    probs = _softmax(logits)
    expected = np.log(
        np.take_along_axis(probs, actions[..., None], axis=-1)[..., 0])
    np.testing.assert_allclose(expected, np.asarray(out), rtol=1e-4, atol=1e-5)


def test_from_logits_equals_importance_weights_path():
    seq_len, batch_size, num_actions = 6, 2, 4
    rng = np.random.RandomState(2)
    behaviour = rng.normal(size=(seq_len, batch_size, num_actions)) \
                   .astype(np.float32)
    target = rng.normal(size=(seq_len, batch_size, num_actions)) \
                .astype(np.float32)
    actions = rng.randint(0, num_actions, (seq_len, batch_size)) \
                 .astype(np.int32)
    base = _make_inputs(seq_len, batch_size)

    out = vtrace.from_logits(
        behaviour_policy_logits=behaviour,
        target_policy_logits=target,
        actions=actions,
        discounts=base["discounts"],
        rewards=base["rewards"],
        values=base["values"],
        bootstrap_value=base["bootstrap_value"])

    log_rhos = (
        np.asarray(vtrace.log_probs_from_logits_and_actions(target, actions))
        - np.asarray(
            vtrace.log_probs_from_logits_and_actions(behaviour, actions)))
    ref = vtrace.from_importance_weights(
        log_rhos=log_rhos,
        discounts=base["discounts"],
        rewards=base["rewards"],
        values=base["values"],
        bootstrap_value=base["bootstrap_value"])

    np.testing.assert_allclose(np.asarray(log_rhos),
                               np.asarray(out.log_rhos), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ref.vs), np.asarray(out.vs),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ref.pg_advantages), np.asarray(out.pg_advantages),
        rtol=1e-5, atol=1e-6)


def test_vtrace_inside_jit_and_grad_stopped():
    """vs/pg_advantages are stop_gradient'ed (reference: vtrace.py:279-280)."""
    inputs = _make_inputs(5, 2)

    def loss_fn(values):
        out = vtrace.from_importance_weights(
            log_rhos=inputs["log_rhos"], discounts=inputs["discounts"],
            rewards=inputs["rewards"], values=values,
            bootstrap_value=inputs["bootstrap_value"])
        return jnp.sum(out.vs) + jnp.sum(out.pg_advantages)

    g = jax.jit(jax.grad(loss_fn))(jnp.asarray(inputs["values"]))
    np.testing.assert_allclose(np.zeros_like(inputs["values"]), np.asarray(g))
