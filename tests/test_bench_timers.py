"""Regression tests for the bench's pipelined micro-timer.

`bench._timed_us_pipelined` carries three subtle correctness
properties that broke (silently, each producing plausible-looking
numbers) during round 4; each is locked in here structurally by
inspecting the lowered program rather than by comparing wall times —
timing comparisons are meaningless on a 1-core CI host and were the
original trap on the remote-TPU link (BENCH_NOTES r4, "Microbench
methodology: four bugs").

1. DCE-proofing: the scan carry must keep EVERY output leaf live, or
   XLA dead-code-eliminates e.g. the whole backward pass of a
   value_and_grad stage (round-4 bug: "grad" timings measured
   forward-only).
2. LICM-proofing: EVERY input leaf must be perturbed by the carry, or
   input-exclusive subcomputation (uint8 frame preprocessing) hoists
   out of the loop.
3. Value-exactness: the perturbations must not change what the stage
   computes (floats get +carry*1e-30, ints +(carry != carry), bools
   ^(carry != carry) — all runtime zero).
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench


def _lowered_scan_text(fn, args, iters=3):
    """Run _timed_us_pipelined while capturing the lowered text of the
    one jitted program it builds."""
    captured = {}
    orig_jit = jax.jit

    def spy(f, *a, **k):
        j = orig_jit(f, *a, **k)

        class Wrap:
            def __call__(self, *ca, **ck):
                if "txt" not in captured:
                    captured["txt"] = j.lower(*ca, **ck).as_text()
                return j(*ca, **ck)

        return Wrap()

    jax.jit = spy
    try:
        bench._timed_us_pipelined(fn, args, iters=iters)
    finally:
        jax.jit = orig_jit
    return captured["txt"]


@pytest.mark.smoke
class TestPipelinedTimerLiveness:
    def test_backward_pass_stays_live(self):
        """value_and_grad over both operands must keep the backward
        dot_generals (1 forward + 2 backward) live in the compiled scan
        body.  Bounds + a forward-only negative control rather than an
        exact count: printer dialects change across JAX releases."""
        x = jnp.asarray(np.random.randn(32, 32).astype(np.float32))
        w = jnp.asarray(np.random.randn(32, 32).astype(np.float32))
        vg = jax.value_and_grad(
            lambda a, b: jnp.sum((a @ b) ** 2), argnums=(0, 1))
        txt = _lowered_scan_text(vg, (x, w))
        fwd_txt = _lowered_scan_text(
            lambda a, b: jnp.sum((a @ b) ** 2), (x, w))
        n_vg = len(re.findall(r"dot_general", txt))
        n_fwd = len(re.findall(r"dot_general", fwd_txt))
        assert n_fwd >= 1
        assert n_vg >= n_fwd + 2, (
            f"backward matmuls missing: {n_vg} dot_generals in "
            f"value_and_grad vs {n_fwd} forward-only")

    def test_unseeded_arg_preprocessing_stays_in_loop(self):
        """uint8 'frames' whose preprocessing depends on no float input
        must still be perturbed (anti-LICM): the integer NE-perturbation
        and the frame->float divide must both appear, and the frames
        arg must be consumed through an add (the perturb), not raw."""
        frames = jnp.asarray(
            np.random.randint(0, 255, (4, 8, 8), np.uint8))
        w = jnp.asarray(np.random.randn(64, 16).astype(np.float32))

        def stage(fr, wt):
            xx = fr.astype(jnp.float32).reshape(4, 64) / 255.0
            return jax.value_and_grad(
                lambda q: jnp.sum((xx @ q) ** 2))(wt)

        txt = _lowered_scan_text(stage, (frames, w))
        # carry != carry (runtime zero); whitespace/dialect-tolerant
        assert re.search(r"compare\s+NE", txt)
        assert re.search(r"\bui?8\b|ui8", txt) and "divide" in txt
        # the perturb add on the uint8 leaf exists inside the program
        assert any(re.search(r"\badd", line)
                   and re.search(r"ui?8", line)
                   for line in txt.splitlines())

    def test_bool_leaves_perturbed(self):
        """bool inputs get the xor-perturbation so a bool-only 'done'
        mask cannot be hoisted (round-4 review finding)."""
        done = jnp.asarray(np.random.rand(16) < 0.5)
        f = jnp.asarray(np.random.randn(16).astype(np.float32))
        txt = _lowered_scan_text(
            lambda d, x: jnp.where(d, x, -x).sum(), (done, f))
        assert any(("xor" in line and re.search(r"i1\b", line))
                   for line in txt.splitlines())

    def test_perturbation_is_value_exact(self):
        """The timed program computes the same value as a direct call
        for float, int, and bool inputs."""
        done = jnp.asarray(np.random.rand(16) < 0.5)
        idx = jnp.asarray(np.random.randint(0, 9, (16,), np.int32))
        f = jnp.asarray(np.random.randn(16, 9).astype(np.float32))

        def stage(d, i, x):
            picked = jnp.take_along_axis(x, i[:, None], axis=1)[:, 0]
            return jnp.where(d, picked, 0.0).sum()

        direct = float(stage(done, idx, f))
        got = {}
        orig_jit = jax.jit

        def spy(fn, *a, **k):
            j = orig_jit(fn, *a, **k)

            def run(*ca, **ck):
                out = j(*ca, **ck)
                got["final_carry"] = out
                return out

            return run

        jax.jit = spy
        try:
            bench._timed_us_pipelined(stage, (done, idx, f), iters=4)
        finally:
            jax.jit = orig_jit
        # every iteration's output feeds the carry; the final carry is
        # the last iteration's value — identical to the direct result.
        assert float(np.asarray(got["final_carry"])) == pytest.approx(
            direct, rel=1e-6)

    def test_timer_returns_nonnegative(self):
        x = jnp.ones((64, 64))
        us, floor_us = bench._timed_us_pipelined(
            lambda a: jnp.tanh(a).sum(), (x,), iters=5)
        assert us >= 0.0
        assert floor_us >= 0.0

    def test_integer_only_outputs_stay_live(self):
        """A stage whose compute feeds ONLY integer outputs (argmax
        actions) must still keep its matmul live — integer leaves fold
        into the carry too (round-4 ADVICE)."""
        x = jnp.asarray(np.random.randn(16, 16).astype(np.float32))
        w = jnp.asarray(np.random.randn(16, 16).astype(np.float32))
        txt = _lowered_scan_text(
            lambda a, b: jnp.argmax(a @ b, axis=-1), (x, w))
        assert re.search(r"dot_general", txt), (
            "integer-only stage was dead-code-eliminated")

    def test_record_timed_clamps_to_floor(self):
        """Sub-resolution readings are published as the floor with an
        explanatory note, never as 0.0 (round-4 VERDICT item 7)."""
        diag = {}
        orig = bench._timed_us_pipelined
        bench._timed_us_pipelined = lambda *a, **k: (0.0, 3.7)
        try:
            bench._record_timed(diag, "kernel_x_us", None, (), 5)
        finally:
            bench._timed_us_pipelined = orig
        assert diag["kernel_x_us"] == 3.7
        assert "below timer resolution" in diag["kernel_x_us_note"]
